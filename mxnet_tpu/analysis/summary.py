"""graftlint phase 1 — per-function summaries and the project call graph.

The lexical rules see one function in one file at a time; the bug
classes the stack actually ships (collective deadlocks from
rank-divergent control flow, lock-ordering cycles across threaded
subsystems, host-effecting calls reached *transitively* from donated
jit/shard_map bodies) are whole-program properties.  This module builds
the substrate the flow rules (phase 2) run over:

* :class:`SummaryCollector` is a pseudo-rule that rides the SAME single
  AST walk the lexical rules use (one parse, one traversal per file)
  and records, per function: calls made (with the locks held and any
  rank-guard active at the call site), locks acquired while holding
  other locks, collectives issued, host-effect calls, and
  ``jit``/``shard_map``/``lax.scan`` body registrations.
* :class:`Program` indexes every module's summaries, resolves call
  sites to summaries (``self.method``, module-level functions, imported
  names, ``self._attr.method`` via ``__init__`` attribute-type
  inference), and computes the transitive closures the flow rules need
  (reaches-a-collective, acquires-locks, host-effects) by worklist
  propagation.

Resolution policy is **open-world**: a call that cannot be resolved
inside the analyzed tree (dynamic dispatch, stdlib, foreign objects) is
assumed benign — it contributes to the ``unresolved_calls`` stat, never
to a finding.  That keeps the flow rules' false-positive rate at the
lexical rules' level: every edge in a reported chain is a real
reference the engine can name.

Stdlib-``ast`` only, like the rest of the package.
"""
from __future__ import annotations

import ast

from .core import Rule, is_lockish_name

# -- token sets --------------------------------------------------------------
# names whose presence in a branch condition marks it rank-divergent:
# different processes of the same SPMD program evaluate it differently
RANK_TOKENS = {
    "process_index", "process_id", "proc_id", "rank", "local_rank",
    "node_rank", "host_id", "is_leader", "is_coordinator", "leader_rank",
}

# collective operations: every rank of the mesh/world must issue them
# in the same order or the program deadlocks
COLLECTIVE_TOKENS = {
    "psum", "psum_scatter", "all_gather", "all_reduce", "reduce_scatter",
    "ppermute", "pmean", "pmax", "pmin", "all_to_all", "barrier",
    "rendezvous", "window_rendezvous",
}

# transforms whose body argument becomes a traced program
_TRACE_TRANSFORMS = {"jit", "shard_map", "pmap"}

# host-effect classification (for trace-host-escape):
_HOST_SYNC_METHODS = {"item", "tolist", "asnumpy", "asscalar",
                      "block_until_ready"}
_NUMPY_BASES = {"np", "numpy", "onp"}
_NUMPY_MATERIALIZERS = {"asarray", "array", "frombuffer", "copy"}
_CLOCK_ATTRS = {"time", "perf_counter", "monotonic", "process_time",
                "sleep"}
_METRIC_METHODS = {"inc", "dec", "observe"}
_METRIC_RECV_TOKENS = ("counter", "gauge", "histogram", "registry",
                       "metric")
_RNG_ATTRS = {"random", "randint", "uniform", "gauss", "normal",
              "choice", "shuffle", "randrange", "sample", "randn"}

# callable names that read as user-supplied callbacks when invoked
# through an unresolvable reference (the callback-under-lock prong):
# the CALLEE name itself (`fn(...)`, `builder(...)`), or a method on a
# plugin-shaped RECEIVER (`rule.evaluate(...)`, `hook.fire(...)`)
HOOKISH_EXACT = {"fn", "cb", "func", "callback", "hook", "probe",
                 "builder"}
HOOKISH_TOKENS = ("hook", "callback", "listener", "handler", "probe")
HOOKISH_RECEIVERS = {"rule", "hook", "probe", "callback", "listener",
                     "handler", "builder", "fn", "cb"}

# builtins: calls to these are resolved-to-nothing, not "unresolved"
_BUILTINS = {
    "len", "isinstance", "getattr", "setattr", "hasattr", "type", "id",
    "str", "repr", "int", "float", "bool", "list", "dict", "set",
    "tuple", "frozenset", "sorted", "reversed", "enumerate", "zip",
    "map", "filter", "range", "min", "max", "sum", "abs", "round",
    "print", "open", "iter", "next", "super", "callable", "vars",
    "format", "divmod", "any", "all", "hash", "ord", "chr", "bytes",
    "bytearray", "memoryview", "object", "property", "staticmethod",
    "classmethod", "issubclass", "delattr", "globals", "locals",
    "exec", "eval", "compile", "slice", "pow", "hex", "oct", "bin",
    "input", "complex", "NotImplementedError", "ValueError",
    "TypeError", "KeyError", "RuntimeError", "OSError", "IOError",
    "Exception", "BaseException", "StopIteration", "KeyboardInterrupt",
    "AttributeError", "IndexError", "NotImplemented", "ArithmeticError",
    "ZeroDivisionError", "OverflowError", "FileNotFoundError",
    "PermissionError", "TimeoutError", "ConnectionError",
    "InterruptedError", "BrokenPipeError", "UnicodeDecodeError",
    "ImportError", "ModuleNotFoundError", "MemoryError",
    "RecursionError", "SystemExit", "GeneratorExit", "AssertionError",
    "LookupError", "NameError", "UnboundLocalError", "EOFError",
}


def module_name_for(path):
    """Dotted module name for a repo-relative path
    (``mxnet_tpu/serving/router.py`` -> ``mxnet_tpu.serving.router``;
    ``pkg/__init__.py`` -> ``pkg``)."""
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [seg for seg in p.split("/") if seg and seg != ".."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<root>"


def _tail(expr):
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _expr_text(expr, limit=48):
    try:
        text = ast.unparse(expr)
    except (ValueError, RecursionError):  # display only; never fail the walk
        text = "<expr>"
    return text if len(text) <= limit else text[:limit - 3] + "..."


class GuardInfo:
    """A rank-divergent branch active at an event site."""

    __slots__ = ("cond", "lineno", "via_return")

    def __init__(self, cond, lineno, via_return=False):
        self.cond = cond          # short source text of the condition
        self.lineno = lineno
        # True when the guard is the REST of a function after a
        # rank-guarded early return/raise (divergent fallthrough)
        self.via_return = via_return


class CallSite:
    """One call expression, with its resolution descriptor and the
    synchronization/divergence context it executes under."""

    __slots__ = ("kind", "parts", "lineno", "col", "held", "guard",
                 "callee", "display")

    def __init__(self, kind, parts, lineno, col, held, guard, display):
        self.kind = kind        # name | self | selfattr | attr | dyn
        self.parts = parts
        self.lineno = lineno
        self.col = col
        self.held = held        # tuple of lock ids held at the call
        self.guard = guard      # GuardInfo or None
        self.display = display  # source text of the callee expression
        self.callee = None      # function id, filled by Program.finish


class LockAcquire:
    __slots__ = ("lock", "held", "lineno", "col")

    def __init__(self, lock, held, lineno, col):
        self.lock = lock
        self.held = held        # tuple of lock ids held when acquiring
        self.lineno = lineno
        self.col = col


class HostEffect:
    __slots__ = ("kind", "detail", "lineno", "col")

    def __init__(self, kind, detail, lineno, col):
        self.kind = kind        # host_sync|numpy|clock|metric|rng|concretize
        self.detail = detail    # e.g. "time.time" or ".item"
        self.lineno = lineno
        self.col = col


class Collective:
    __slots__ = ("kind", "lineno", "col", "guard", "held")

    def __init__(self, kind, lineno, col, guard, held):
        self.kind = kind
        self.lineno = lineno
        self.col = col
        self.guard = guard
        self.held = held


class TracedReg:
    """A jit/shard_map/scan body registration site."""

    __slots__ = ("transform", "kind", "parts", "lineno")

    def __init__(self, transform, kind, parts, lineno):
        self.transform = transform
        self.kind = kind
        self.parts = parts
        self.lineno = lineno


class FunctionSummary:
    __slots__ = ("id", "module", "path", "qual", "name", "lineno",
                 "class_name", "parent", "children", "calls",
                 "collectives", "host_effects", "lock_acquires",
                 "traced_regs", "is_traced_root", "rest_guard",
                 "ast_node")

    def __init__(self, fid, module, path, qual, name, lineno,
                 class_name=None, parent=None):
        self.id = fid
        self.module = module
        self.path = path
        self.qual = qual          # dotted within the module
        self.name = name
        self.lineno = lineno
        self.class_name = class_name
        self.parent = parent      # enclosing function id, or None
        self.children = {}        # nested def name -> function id
        self.calls = []
        self.collectives = []
        self.host_effects = []
        self.lock_acquires = []
        self.traced_regs = []
        self.is_traced_root = False   # @jit-style decorated
        self.rest_guard = None        # GuardInfo after guarded return
        self.ast_node = None          # def node (lifecycle CFG input)

    def __repr__(self):
        return f"FunctionSummary({self.id})"


class ClassInfo:
    __slots__ = ("name", "module", "bases", "methods", "attr_types",
                 "lock_attrs")

    def __init__(self, name, module, bases):
        self.name = name
        self.module = module
        self.bases = bases        # base-class expression tails
        self.methods = {}         # method name -> function id
        self.attr_types = {}      # self.<attr> -> type descriptor
        self.lock_attrs = set()   # attrs assigned from Lock factories


class ModuleInfo:
    __slots__ = ("name", "path", "package", "imports", "classes",
                 "toplevel", "module_summary")

    def __init__(self, name, path, is_pkg):
        self.name = name
        self.path = path
        self.package = name if is_pkg else name.rpartition(".")[0]
        self.imports = {}         # local name -> ("import", dotted) |
        #                           ("from", base_module, name)
        self.classes = {}         # class name -> ClassInfo
        self.toplevel = {}        # module-level function name -> id
        self.module_summary = None


# -- the collector (rides the shared walk) -----------------------------------
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}


class _Frame:
    """Per-function walk state: where this function's lock/guard
    context starts (events inside a nested def must not inherit the
    enclosing function's ``with``/``if`` context — the body runs
    later), and the rank-tainted local names."""

    __slots__ = ("summary", "lock_base", "guard_base", "taint")

    def __init__(self, summary, lock_base, guard_base):
        self.summary = summary
        self.lock_base = lock_base
        self.guard_base = guard_base
        self.taint = set()


class SummaryCollector(Rule):
    """Not a lint rule — a collector sharing the single walk.  It is
    appended to the rule list by ``analyze_project`` and never reports
    findings of its own."""

    id = "_summary-collector"
    severity = "info"
    doc = "internal: builds per-function summaries for the flow rules"

    def __init__(self, program):
        self.program = program

    # -- file lifecycle ------------------------------------------------------
    def begin_file(self, ctx):
        is_pkg = ctx.path.endswith("__init__.py")
        self.mod = ModuleInfo(module_name_for(ctx.path), ctx.path, is_pkg)
        self.program.add_module(self.mod)
        mod_summary = FunctionSummary(
            f"{self.mod.name}::<module>", self.mod.name, ctx.path,
            "<module>", "<module>", 0)
        self.mod.module_summary = mod_summary
        self.program.add_function(mod_summary)
        self.frames = [_Frame(mod_summary, 0, 0)]
        self.name_stack = []
        self.class_infos = []     # ClassInfo stack
        self.lock_stack = []      # (with-node, [lock ids])
        self.guard_stack = []     # (if-node, GuardInfo)

    def end_file(self, ctx):
        self.frames = self.frames[:1]
        self.lock_stack = []
        self.guard_stack = []

    # -- context helpers -----------------------------------------------------
    @property
    def _frame(self):
        return self.frames[-1]

    def _held(self):
        frame = self._frame
        out = []
        for _node, ids in self.lock_stack[frame.lock_base:]:
            out.extend(ids)
        return tuple(out)

    def _guard(self):
        frame = self._frame
        for _node, g in reversed(self.guard_stack[frame.guard_base:]):
            if g is not None:
                return g
        return frame.summary.rest_guard

    def _lock_id(self, expr, ctx):
        """Stable identity for a lock expression, or None.

        ``self._lock`` -> ``<module>.<Class>._lock`` (ordering
        discipline is per-class: every instance of the class must
        acquire in the same order); a module-level name ->
        ``<module>.<name>``.  Other receivers (``obj.attr``) cannot be
        aliased statically and stay inert (no edges)."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            cls = ctx.current_class
            owner = cls.name if cls is not None else "<self>"
            return f"{self.mod.name}.{owner}.{expr.attr}"
        if isinstance(expr, ast.Name):
            return f"{self.mod.name}.{expr.id}"
        return None

    def _is_lock_expr(self, expr, ctx):
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            if is_lockish_name(expr.attr):
                return True
            cls = self.class_infos[-1] if self.class_infos else None
            return cls is not None and expr.attr in cls.lock_attrs
        if isinstance(expr, ast.Name):
            return is_lockish_name(expr.id)
        return False

    # -- rank-condition detection --------------------------------------------
    def _rank_tokens_in(self, test):
        """Token(s) that make ``test`` rank-divergent, or []."""
        taint = self._frame.taint
        found = []
        for node in ast.walk(test):
            if isinstance(node, ast.Name):
                if node.id in RANK_TOKENS or node.id in taint:
                    found.append(node.id)
            elif isinstance(node, ast.Attribute):
                if node.attr in RANK_TOKENS or \
                        node.attr.startswith("local_"):
                    found.append(node.attr)
        return found

    # -- call classification -------------------------------------------------
    @staticmethod
    def _descriptor(func):
        """(kind, parts) resolution descriptor for a callee expression."""
        if isinstance(func, ast.Name):
            return "name", (func.id,)
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id == "self":
                    return "self", (func.attr,)
                return "attr", (base.id, func.attr)
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self":
                # self._attr.method() — resolvable via attr-type
                # inference from __init__ assignments
                return "selfattr", (base.attr, func.attr)
            return "dyn", (func.attr,)
        return "dyn", ("<call>",)

    def _record_call(self, node, ctx):
        func = node.func
        kind, parts = self._descriptor(func)
        summary = self._frame.summary
        site = CallSite(kind, parts, node.lineno, node.col_offset,
                        self._held(), self._guard(), _expr_text(func))
        summary.calls.append(site)

        tail = _tail(func)
        # collectives (every rank must reach them)
        if tail in COLLECTIVE_TOKENS:
            summary.collectives.append(Collective(
                tail, node.lineno, node.col_offset, site.guard,
                site.held))

        # host effects (trace-host-escape raw material)
        self._record_host_effect(node, func, tail, summary)

        # traced-body registrations: jit(f)/shard_map(f,...)/lax.scan(f)
        if tail in _TRACE_TRANSFORMS and node.args:
            summary.traced_regs.append(TracedReg(
                tail, *self._descriptor_expr(node.args[0]), node.lineno))
        elif tail == "scan" and isinstance(func, ast.Attribute) and \
                _tail(func.value) == "lax" and node.args:
            summary.traced_regs.append(TracedReg(
                "scan", *self._descriptor_expr(node.args[0]), node.lineno))

    @staticmethod
    def _descriptor_expr(expr):
        """Descriptor for a function VALUE (registration argument)."""
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return SummaryCollector._descriptor(
                expr if not isinstance(expr, ast.Call) else expr.func)
        return "dyn", ("<expr>",)

    def _record_host_effect(self, node, func, tail, summary):
        effect = None
        if isinstance(func, ast.Attribute):
            base = func.value
            base_tail = _tail(base)
            if tail in _HOST_SYNC_METHODS:
                effect = ("host_sync", f".{tail}")
            elif base_tail in _NUMPY_BASES and \
                    tail in _NUMPY_MATERIALIZERS:
                effect = ("numpy", f"{base_tail}.{tail}")
            elif base_tail == "time" and tail in _CLOCK_ATTRS:
                effect = ("clock", f"time.{tail}")
            elif tail in _METRIC_METHODS:
                effect = ("metric", f".{tail}")
            elif tail == "set":
                recv = _expr_text(base).lower()
                if any(t in recv for t in _METRIC_RECV_TOKENS):
                    effect = ("metric", ".set")
            elif tail in _RNG_ATTRS and (
                    (isinstance(base, ast.Name) and base.id == "random")
                    or (isinstance(base, ast.Attribute)
                        and base.attr == "random"
                        and _tail(base.value) in _NUMPY_BASES)):
                # stdlib `random.x()` / `np.random.x()` only —
                # `jax.random.*` is a traced PRNG op, not a host draw
                effect = ("rng", f"{_expr_text(base)}.{tail}")
        elif isinstance(func, ast.Name):
            if func.id in ("float", "int", "bool") and \
                    len(node.args) == 1 and \
                    isinstance(node.args[0], ast.Name):
                # only parameter-derived names: float(cfg) of a python
                # scalar is fine, float(x) of a likely-array argument
                # concretizes (the tracer-leak rule owns the decorated
                # depth-0 form; this records it for call chains)
                effect = ("concretize", f"{func.id}()")
        if effect is not None:
            summary.host_effects.append(HostEffect(
                effect[0], effect[1], node.lineno, node.col_offset))

    # -- the walk ------------------------------------------------------------
    def visit(self, node, ctx):
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else \
                    alias.name.split(".")[0]
                self.mod.imports[local] = ("import", target)
        elif isinstance(node, ast.ImportFrom):
            base = self._from_base(node)
            for alias in node.names:
                if alias.name == "*":
                    continue
                self.mod.imports[alias.asname or alias.name] = \
                    ("from", base, alias.name)
        elif isinstance(node, ast.ClassDef):
            info = ClassInfo(node.name, self.mod.name,
                             [_tail(b) for b in node.bases])
            self.mod.classes.setdefault(node.name, info)
            self.class_infos.append(info)
            self.name_stack.append(node.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._enter_function(node, ctx)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            ids = []
            held = list(self._held())
            for item in node.items:
                if not self._is_lock_expr(item.context_expr, ctx):
                    continue
                lid = self._lock_id(item.context_expr, ctx)
                if lid is None:
                    continue
                self._frame.summary.lock_acquires.append(LockAcquire(
                    lid, tuple(held), node.lineno, node.col_offset))
                ids.append(lid)
                held.append(lid)
            self.lock_stack.append((node, ids))
        elif isinstance(node, ast.If):
            tokens = self._rank_tokens_in(node.test)
            if tokens:
                self.guard_stack.append((node, GuardInfo(
                    _expr_text(node.test), node.lineno)))
            else:
                self.guard_stack.append((node, None))
        elif isinstance(node, ast.Assign):
            self._record_assign(node, ctx)
        elif isinstance(node, ast.Call):
            self._record_call(node, ctx)

    def depart(self, node, ctx):
        if isinstance(node, ast.ClassDef):
            if self.class_infos and self.name_stack and \
                    self.name_stack[-1] == node.name:
                self.class_infos.pop()
                self.name_stack.pop()
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if len(self.frames) > 1 and \
                    self._frame.summary.name == node.name:
                self.frames.pop()
                self.name_stack.pop()
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            if self.lock_stack and self.lock_stack[-1][0] is node:
                self.lock_stack.pop()
        elif isinstance(node, ast.If):
            if self.guard_stack and self.guard_stack[-1][0] is node:
                _n, guard = self.guard_stack.pop()
                if guard is not None and not node.orelse and \
                        node.body and isinstance(
                            node.body[-1], (ast.Return, ast.Raise)):
                    # `if rank != 0: return` — the REST of the function
                    # is rank-divergent fallthrough
                    frame = self._frame
                    if frame.summary.rest_guard is None:
                        frame.summary.rest_guard = GuardInfo(
                            guard.cond, guard.lineno, via_return=True)

    # -- helpers -------------------------------------------------------------
    def _from_base(self, node):
        if node.level == 0:
            return node.module or ""
        parts = self.mod.package.split(".") if self.mod.package else []
        if node.level > 1:
            parts = parts[:len(parts) - (node.level - 1)]
        if node.module:
            parts = parts + node.module.split(".")
        return ".".join(parts)

    def _enter_function(self, node, ctx):
        self.name_stack.append(node.name)
        qual = ".".join(self.name_stack)
        fid = f"{self.mod.name}::{qual}"
        cls = self.class_infos[-1] if self.class_infos else None
        parent = self._frame.summary if len(self.frames) > 1 or \
            self._frame.summary.qual != "<module>" else None
        summary = FunctionSummary(
            fid, self.mod.name, self.mod.path, qual, node.name,
            node.lineno,
            class_name=cls.name if cls is not None else None,
            parent=parent.id if parent is not None else None)
        summary.ast_node = node   # lifecycle builds its CFG lazily
        for dec in node.decorator_list:
            dtail = _tail(dec)
            if dtail in _TRACE_TRANSFORMS:
                summary.is_traced_root = True
            elif isinstance(dec, ast.Call):
                ftail = _tail(dec.func)
                if ftail in _TRACE_TRANSFORMS:
                    summary.is_traced_root = True
                elif ftail == "partial" and dec.args and \
                        _tail(dec.args[0]) in _TRACE_TRANSFORMS:
                    summary.is_traced_root = True
        self.program.add_function(summary)
        # register with the enclosing scope for name resolution
        if parent is not None:
            parent.children[node.name] = fid
        if cls is not None and len(self.name_stack) >= 2 and \
                self.name_stack[-2] == cls.name:
            cls.methods.setdefault(node.name, fid)
        elif parent is None:
            self.mod.toplevel.setdefault(node.name, fid)
        self.frames.append(_Frame(summary, len(self.lock_stack),
                                  len(self.guard_stack)))

    def _record_assign(self, node, ctx):
        value = node.value
        # rank taint: names assigned from a rank-bearing expression
        if isinstance(value, (ast.Call, ast.Attribute, ast.Name,
                              ast.BinOp, ast.Compare)):
            rankish = False
            for sub in ast.walk(value):
                if isinstance(sub, ast.Name) and (
                        sub.id in RANK_TOKENS
                        or sub.id in self._frame.taint):
                    rankish = True
                    break
                if isinstance(sub, ast.Attribute) and \
                        sub.attr in RANK_TOKENS:
                    rankish = True
                    break
            if rankish:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._frame.taint.add(t.id)
        # self.<attr> = <Type>(...): attribute-type inference, plus
        # lock-factory marking for non-lockish names (self._mu = Lock())
        cls = self.class_infos[-1] if self.class_infos else None
        if cls is None or not isinstance(value, ast.Call):
            return
        vtail = _tail(value.func)
        for t in node.targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                if vtail in _LOCK_FACTORIES:
                    cls.lock_attrs.add(t.attr)
                elif vtail and vtail[0].isupper():
                    cls.attr_types.setdefault(
                        t.attr, self._descriptor(value.func))


# -- the program (phase-2 substrate) ----------------------------------------
class Program:
    """Every module's summaries plus the resolved call graph and the
    transitive closures the flow rules consume."""

    def __init__(self):
        self.modules = {}         # module name -> ModuleInfo
        self.functions = {}       # function id -> FunctionSummary
        self.edges = 0
        self.unresolved_calls = 0
        self.callers = {}         # callee id -> [(caller id, CallSite)]
        self.collective_closure = {}   # fid -> (kind, path, line, chain)
        self.lock_closure = {}    # fid -> {lock: (path, line, chain)}
        self.traced_roots = []    # FunctionSummary list

    def add_module(self, mod):
        self.modules[mod.name] = mod

    def add_function(self, fs):
        self.functions[fs.id] = fs

    def stats(self):
        return {"functions": len(self.functions), "edges": self.edges,
                "unresolved_calls": self.unresolved_calls}

    # -- resolution ----------------------------------------------------------
    def finish(self):
        """Resolve every call site and compute the closures.  Called
        once, after every file has been walked."""
        for fs in self.functions.values():
            mod = self.modules.get(fs.module)
            if mod is None:
                continue
            for call in fs.calls:
                callee = self._resolve(mod, fs, call)
                if callee is _BENIGN:
                    continue
                if callee is None:
                    self.unresolved_calls += 1
                else:
                    call.callee = callee
                    self.edges += 1
                    self.callers.setdefault(callee, []).append(
                        (fs.id, call))
        self._compute_collective_closure()
        self._compute_lock_closure()
        self._collect_traced_roots()
        return self

    def _resolve(self, mod, fs, call):
        kind, parts = call.kind, call.parts
        if kind == "name":
            name = parts[0]
            if name in _BUILTINS:
                return _BENIGN
            # lexical scope chain: nested defs of enclosing functions
            cur = fs
            while cur is not None:
                if name in cur.children:
                    return cur.children[name]
                cur = self.functions.get(cur.parent) \
                    if cur.parent else None
            if name in mod.toplevel:
                return mod.toplevel[name]
            if name in mod.classes:
                return mod.classes[name].methods.get("__init__", _BENIGN)
            return self._resolve_import(mod, name, None)
        if kind == "self":
            return self._resolve_method(mod, fs.class_name, parts[0])
        if kind == "selfattr":
            attr, meth = parts
            cls = mod.classes.get(fs.class_name or "")
            if cls is None or attr not in cls.attr_types:
                return None
            tkind, tparts = cls.attr_types[attr]
            tname = tparts[-1]
            owner_mod = mod
            if tname not in mod.classes:
                target = self._resolve_import_module(mod, tkind, tparts)
                if target is None:
                    return None
                owner_mod, tname = target
            return self._resolve_method_in(owner_mod, tname, meth)
        if kind == "attr":
            base, attr = parts
            if base in mod.classes:
                return mod.classes[base].methods.get(attr)
            return self._resolve_import(mod, base, attr)
        return None

    def _resolve_method(self, mod, class_name, meth, depth=0):
        return self._resolve_method_in(mod, class_name or "", meth, depth)

    def _resolve_method_in(self, mod, class_name, meth, depth=0):
        if depth > 4:
            return None
        cls = mod.classes.get(class_name)
        if cls is None:
            return None
        if meth in cls.methods:
            return cls.methods[meth]
        for base in cls.bases:
            if base in mod.classes:
                found = self._resolve_method_in(mod, base, meth,
                                                depth + 1)
            else:
                target = self._resolve_import_module(
                    mod, "name", (base,))
                found = None if target is None else \
                    self._resolve_method_in(target[0], target[1],
                                            meth, depth + 1)
            if found is not None:
                return found
        return None

    def _resolve_import(self, mod, base, attr):
        """Resolve ``base(...)`` (attr=None) or ``base.attr(...)``
        through the module's import table."""
        imp = mod.imports.get(base)
        if imp is None:
            return None
        if imp[0] == "import":
            target = self.modules.get(imp[1])
            if target is None or attr is None:
                return None
            return self._module_attr(target, attr)
        _kind, from_mod, sym = imp
        submodule = self.modules.get(f"{from_mod}.{sym}")
        if submodule is not None:
            # `from pkg import mod` — base names a module
            return None if attr is None else \
                self._module_attr(submodule, attr)
        target = self.modules.get(from_mod)
        if target is None:
            return None
        if attr is None:
            return self._module_attr(target, sym)
        # `from m import Cls` then `Cls.method(...)`
        if sym in target.classes:
            return target.classes[sym].methods.get(attr)
        return None

    def _module_attr(self, mod, attr):
        if attr in mod.toplevel:
            return mod.toplevel[attr]
        if attr in mod.classes:
            return mod.classes[attr].methods.get("__init__", _BENIGN)
        return None

    def _resolve_import_module(self, mod, kind, parts):
        """-> (ModuleInfo, class name) for a type descriptor, or
        None."""
        name = parts[-1]
        if kind == "attr":
            imp = mod.imports.get(parts[0])
            if imp is not None:
                target = None
                if imp[0] == "import":
                    target = self.modules.get(imp[1])
                else:
                    target = self.modules.get(f"{imp[1]}.{imp[2]}") or \
                        self.modules.get(imp[1])
                if target is not None and name in target.classes:
                    return target, name
            return None
        imp = mod.imports.get(name)
        if imp is not None and imp[0] == "from":
            target = self.modules.get(imp[1])
            if target is not None and imp[2] in target.classes:
                return target, imp[2]
        return None

    # -- closures ------------------------------------------------------------
    def _compute_collective_closure(self):
        """fid -> (kind, path, line, chain of function names) for the
        nearest collective reachable from the function (itself
        included); BFS over reverse edges keeps chains shortest."""
        closure = {}
        worklist = []
        for fs in self.functions.values():
            if fs.collectives:
                c = fs.collectives[0]
                closure[fs.id] = (c.kind, fs.path, c.lineno, (fs.name,))
                worklist.append(fs.id)
        while worklist:
            fid = worklist.pop(0)
            kind, path, line, chain = closure[fid]
            if len(chain) > 12:
                continue
            for caller_id, _site in self.callers.get(fid, ()):
                if caller_id in closure:
                    continue
                caller = self.functions[caller_id]
                closure[caller_id] = (kind, path, line,
                                      (caller.name,) + chain)
                worklist.append(caller_id)
        self.collective_closure = closure

    def _compute_lock_closure(self):
        """fid -> {lock id: (path, line, chain)} — every lock a
        function may acquire, directly or via calls."""
        closure = {}
        worklist = []
        for fs in self.functions.values():
            if fs.lock_acquires:
                acc = {}
                for la in fs.lock_acquires:
                    acc.setdefault(la.lock,
                                   (fs.path, la.lineno, (fs.name,)))
                closure[fs.id] = acc
                worklist.append(fs.id)
        while worklist:
            fid = worklist.pop(0)
            for caller_id, _site in self.callers.get(fid, ()):
                caller = self.functions[caller_id]
                acc = closure.setdefault(caller_id, {})
                changed = False
                for lock, (path, line, chain) in closure[fid].items():
                    if lock not in acc and len(chain) <= 12:
                        acc[lock] = (path, line, (caller.name,) + chain)
                        changed = True
                if changed:
                    worklist.append(caller_id)
        self.lock_closure = closure

    def _collect_traced_roots(self):
        roots = {}
        for fs in self.functions.values():
            if fs.is_traced_root:
                roots.setdefault(fs.id, fs)
            mod = self.modules.get(fs.module)
            for reg in fs.traced_regs:
                target = None
                if mod is not None:
                    probe = CallSite(reg.kind, reg.parts, reg.lineno,
                                     0, (), None, "")
                    target = self._resolve(mod, fs, probe)
                if target is not None and target is not _BENIGN:
                    tf = self.functions.get(target)
                    if tf is not None:
                        roots.setdefault(tf.id, tf)
        self.traced_roots = list(roots.values())


class _Benign:
    """Sentinel: resolved to something known-harmless (builtin, class
    with no __init__) — not an edge, not an unresolved call."""

    __repr__ = lambda self: "<benign>"  # noqa: E731


_BENIGN = _Benign()
