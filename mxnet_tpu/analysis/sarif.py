"""SARIF 2.1.0 output for graftlint findings.

``tools/graftlint.py --sarif <path>`` writes one run in the static
analysis results interchange format so CI can annotate PRs with any
SARIF-aware viewer.  Design points:

* every REGISTERED rule appears in ``tool.driver.rules`` (not just the
  rules that fired) — viewers resolve ``ruleIndex`` against it, and a
  clean run still documents what was checked.  Rich catalog entries
  (``analysis/catalog.py``) supply ``fullDescription``; rules without
  one fall back to their registry one-liner;
* graftlint fingerprints (``rule|path|symbol`` — stable across line
  drift) go into ``partialFingerprints`` under
  ``graftlintFingerprint/v1`` so SARIF baselining matches the native
  baseline mechanics;
* severities map error→error, warning→warning, info→note;
* artifact URIs are repo-relative (graftlint already normalizes to
  forward slashes) with a ``uriBaseId`` of ``SRCROOT``.
"""
from __future__ import annotations

from . import catalog
from .core import all_graph_rules, all_rules

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")
FINGERPRINT_KEY = "graftlintFingerprint/v1"

_LEVEL = {"error": "error", "warning": "warning", "info": "note"}


def _rule_descriptor(cls):
    ent = catalog.get(cls.id)
    desc = {
        "id": cls.id,
        "shortDescription": {"text": cls.doc},
        "defaultConfiguration": {
            "level": _LEVEL.get(cls.severity, "warning"),
        },
        "helpUri": "docs/lint.md",
    }
    if ent is not None:
        desc["fullDescription"] = {"text": ent.description}
        desc["help"] = {"markdown": catalog.render_entry(cls.id)}
    return desc


def render_sarif(findings, tool_version="3"):
    """The SARIF 2.1.0 document (a plain dict — json.dump it)."""
    rules = {}
    rules.update(all_rules())
    rules.update(all_graph_rules())
    ordered = sorted(rules.values(), key=lambda c: c.id)
    index = {cls.id: i for i, cls in enumerate(ordered)}

    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": _LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path,
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {
                        "startLine": max(1, f.line),
                        "startColumn": max(1, f.col + 1),
                    },
                },
            }],
            "partialFingerprints": {FINGERPRINT_KEY: f.fingerprint},
        }
        if f.rule in index:
            result["ruleIndex"] = index[f.rule]
        results.append(result)

    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graftlint",
                    "version": tool_version,
                    "informationUri": "docs/lint.md",
                    "rules": [_rule_descriptor(c) for c in ordered],
                },
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }
