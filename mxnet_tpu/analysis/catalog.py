"""graftlint rule catalog — the single source of truth for the rich
rule documentation shared by ``tools/graftlint.py --explain <rule>``
and the ``docs/lint.md`` catalog section.

Each entry carries the prose a triager needs at the moment a finding
fires: what the rule proves, the origin bug that motivated it, one
minimal example that flags, and one near-miss that deliberately stays
silent.  ``render_entry`` produces the exact markdown block embedded
in ``docs/lint.md`` (a drift-guard test in ``tests/test_graftlint.py``
compares them byte-for-byte), and ``explain`` prints the same block on
the CLI — docs and CLI cannot drift because they are the same string.

Rules without an entry here fall back to their one-line registry
``doc`` in ``--explain`` (the v2 rules keep their hand-written docs
sections; new rules must add an entry)."""
from __future__ import annotations

from .core import all_graph_rules, all_rules


class CatalogEntry:
    __slots__ = ("rule", "title", "description", "origin", "example",
                 "near_miss")

    def __init__(self, rule, title, description, origin, example,
                 near_miss):
        self.rule = rule
        self.title = title
        self.description = description
        self.origin = origin
        self.example = example
        self.near_miss = near_miss


_ENTRIES = {}


def _entry(**kw):
    ent = CatalogEntry(**kw)
    _ENTRIES[ent.rule] = ent
    return ent


_entry(
    rule="resource-leak-on-raise",
    title="acquired resource reaches the exceptional exit unreleased",
    description=(
        "The lifecycle dataflow (analysis/lifecycle.py) tracks every "
        "protocol-table resource — KV-slot handles, trace spans, bare "
        "`open()` files, `Thread` handles, keyed `LEDGER.add/release` "
        "byte pairs, bare `lock.acquire()` outside `with`, chaos "
        "failpoint arm/disarm — through the per-function CFG "
        "(analysis/cfg.py), including the implicit exception edge out "
        "of every call site.  The rule fires when SOME exception path "
        "from after the acquire reaches the function's exceptional "
        "exit with neither a release nor an ownership transfer "
        "(return / yield / stored on an attribute / passed to a "
        "callee) on that path.  Releases inside `finally` cover both "
        "edges (the CFG inlines finally bodies per path); `with` "
        "acquisitions are never tracked; the acquire statement's own "
        "exception edge carries the pre-acquire state; unresolved "
        "callees are open-world and silent."),
    origin=(
        "ISSUE 18 triage: `GenerationEngine.start_session` started "
        "the session trace span, then ran `KVSlotPool.acquire` under "
        "it — admission-control rejections (pool exhausted) left the "
        "span unfinished, leaking a phantom in-flight session into "
        "the tracer's active set on every shed request."),
    example=(
        "def serve(pool):\n"
        "    slot = pool.acquire(\"s\", 4)\n"
        "    risky()            # raises -> slot never released\n"
        "    pool.release(slot)"),
    near_miss=(
        "def serve(pool):\n"
        "    slot = pool.acquire(\"s\", 4)\n"
        "    try:\n"
        "        risky()\n"
        "    finally:\n"
        "        pool.release(slot)   # covers the exception edge"),
)

_entry(
    rule="double-release",
    title="every path into a release has already released",
    description=(
        "A must-analysis on the same lifecycle dataflow: the rule "
        "fires at a release site only when the abstract state set "
        "arriving there is non-empty and ALL-released — i.e. every "
        "feasible path already released the resource, so the second "
        "release is dead code or split ownership (two owners each "
        "believing they hold the slot).  Guarded patterns stay "
        "silent because a join that still carries an acquired or "
        "unacquired branch is not all-released: `if f: f.close()` "
        "after a conditional close, handler-release + finally-release "
        "separated by the CFG's per-path finally duplication.  "
        "Legitimately repeatable protocols (Thread.join, accumulative "
        "keyed ledger pairs) are excluded."),
    origin=(
        "ISSUE 18 triage: `KVSlotPool.release` is idempotent by "
        "design for chaos teardown, which silently absorbs what "
        "should be an ownership crash — a path that releases the "
        "same slot twice means two owners, and the pool's "
        "idempotence hides it until page accounting drifts."),
    example=(
        "def teardown(pool, slot):\n"
        "    pool.release(slot)\n"
        "    pool.release(slot)   # every path already released"),
    near_miss=(
        "def teardown(pool, slot, dirty):\n"
        "    if dirty:\n"
        "        pool.release(slot)\n"
        "    if dirty:            # join carries the unreleased branch\n"
        "        return\n"
        "    pool.release(slot)"),
)

_entry(
    rule="release-under-wrong-lock",
    title="paired acquire and release disagree on held locks",
    description=(
        "For every acquire/release pairing the lifecycle engine "
        "proves inside one function, compare the held-lock sets the "
        "PR 15 summaries recorded at the two call sites.  In a "
        "threaded subsystem (same path gate as lock-order-cycle) a "
        "mismatch means either the release takes locks the acquire "
        "proved unnecessary (new deadlock surface against the "
        "exporter/scrape path) or the acquire relied on a lock the "
        "release doesn't honor (torn accounting).  Silent when both "
        "sites are lock-free, when both run under the identical lock "
        "(`with self._lock:` around both halves), and outside the "
        "threaded prefixes."),
    origin=(
        "ISSUE 18 triage: `KVSlotPool` deliberately charges the "
        "ledger AFTER dropping the pool lock (PR 16 — never call the "
        "accounting layer under a pool lock, the exporter scrapes "
        "it); a release path that slips `LEDGER.release` back under "
        "the pool lock reintroduces the exact deadlock the design "
        "dodged, visible only when a scrape lands mid-release."),
    example=(
        "# mxnet_tpu/serving/pool.py\n"
        "def grab(self):\n"
        "    h = self.pool.acquire(\"s\", 4)   # lock-free by design\n"
        "    with self._lock:\n"
        "        self.pool.release(h)        # now under _lock"),
    near_miss=(
        "# mxnet_tpu/serving/pool.py\n"
        "def grab(self):\n"
        "    with self._lock:\n"
        "        h = self.pool.acquire(\"s\", 4)\n"
        "        self.pool.release(h)        # same lock both sites"),
)


def entries():
    """All catalog entries, by rule id."""
    return dict(_ENTRIES)


def get(rule_id):
    return _ENTRIES.get(rule_id)


def _registered(rule_id):
    cls = all_rules().get(rule_id)
    if cls is None:
        cls = all_graph_rules().get(rule_id)
    return cls


def _severity_of(rule_id):
    cls = _registered(rule_id)
    return cls.severity if cls is not None else None


def _doc_of(rule_id):
    cls = _registered(rule_id)
    return cls.doc if cls is not None else None


def render_entry(rule_id):
    """The markdown block for one rule — byte-identical to the block
    embedded in docs/lint.md (drift-guard tested)."""
    ent = _ENTRIES.get(rule_id)
    sev = _severity_of(rule_id) or "warning"
    if ent is None:
        return None
    return (
        f"### `{ent.rule}` ({sev}) — {ent.title}\n"
        f"\n"
        f"**Origin:** {ent.origin}\n"
        f"\n"
        f"{ent.description}\n"
        f"\n"
        f"**Flags:**\n"
        f"\n"
        f"```python\n{ent.example}\n```\n"
        f"\n"
        f"**Stays silent (near-miss):**\n"
        f"\n"
        f"```python\n{ent.near_miss}\n```\n")


def explain(rule_id):
    """The --explain payload: the catalog block, or the registry
    one-liner for rules without a rich entry; None for unknown ids."""
    block = render_entry(rule_id)
    if block is not None:
        return block
    doc = _doc_of(rule_id)
    if doc is None:
        return None
    sev = _severity_of(rule_id)
    return (f"### `{rule_id}` ({sev})\n\n{doc}\n\n"
            "(no rich catalog entry — see docs/lint.md)\n")
