"""tracer-leak — traced values escaping or being concretized inside
``jit``/``shard_map``-decorated functions.

Inside a function compiled by ``jax.jit`` (or ``shard_map``/``pmap``)
the arguments are tracers.  Three classic bugs:

* **storing a tracer** on ``self`` or a global: the reference outlives
  the trace and either raises ``UnexpectedTracerError`` later or
  silently pins stale compile-time state;
* **Python branching** (``if``/``while``/``assert``) on a traced value:
  forces concretization — a ``ConcretizationTypeError`` at best, a
  silently trace-time-frozen branch at worst;
* **host concretization** — ``float()``/``int()``/``bool()``/
  ``.item()``/``.tolist()`` on a traced argument.

Near-misses that stay silent: branching on parameters named in
``static_argnames``/``static_argnums`` (they are Python values, not
tracers), and branching on *static metadata* of a traced value —
``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``x.size`` / ``len(x)`` /
``isinstance(x, ...)`` are trace-time constants.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

_JIT_NAMES = {"jit", "shard_map", "pmap"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "weak_type"}
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type",
                 "callable", "id"}
_CONCRETIZERS = {"float", "int", "bool"}
_CONCRETIZER_METHODS = {"item", "tolist"}


def _tail_name(expr):
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


def _jit_decoration(dec):
    """-> (static_names, static_nums) when ``dec`` marks a jit-like
    transform, else None.  Handles ``@jit``, ``@jax.jit``,
    ``@jax.jit(...)`` and ``@functools.partial(jax.jit, ...)``."""
    if _tail_name(dec) in _JIT_NAMES:
        return set(), set()
    if not isinstance(dec, ast.Call):
        return None
    statics_from = None
    if _tail_name(dec.func) in _JIT_NAMES:
        statics_from = dec
    elif _tail_name(dec.func) == "partial" and dec.args \
            and _tail_name(dec.args[0]) in _JIT_NAMES:
        statics_from = dec
    if statics_from is None:
        return None
    names, nums = set(), set()
    for kw in statics_from.keywords:
        val = kw.value
        if kw.arg == "static_argnames":
            for elt in (val.elts if isinstance(val, (ast.Tuple, ast.List))
                        else [val]):
                if isinstance(elt, ast.Constant) and \
                        isinstance(elt.value, str):
                    names.add(elt.value)
        elif kw.arg == "static_argnums":
            for elt in (val.elts if isinstance(val, (ast.Tuple, ast.List))
                        else [val]):
                if isinstance(elt, ast.Constant) and \
                        isinstance(elt.value, int):
                    nums.add(elt.value)
    return names, nums


def _traced_params(func, static_names, static_nums):
    args = func.args
    positional = [a.arg for a in args.posonlyargs + args.args]
    traced = {name for i, name in enumerate(positional)
              if i not in static_nums and name not in static_names}
    traced.update(a.arg for a in args.kwonlyargs
                  if a.arg not in static_names)
    traced.discard("self")
    return traced


def _offending_names(test, traced):
    """Names of traced params used as *values* (not via static metadata)
    in a branch test expression."""
    bad = []

    def rec(node, safe):
        if isinstance(node, ast.Attribute):
            rec(node.value, node.attr in _STATIC_ATTRS or safe)
            return
        if isinstance(node, ast.Call):
            fname = _tail_name(node.func)
            safe_call = fname in _STATIC_CALLS
            if isinstance(node.func, ast.Attribute):
                # x.sum() etc. produces a traced value — the receiver
                # itself is being used as a value
                rec(node.func.value, safe_call)
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                rec(a, safe_call)
            return
        if isinstance(node, ast.Name):
            if not safe and node.id in traced:
                bad.append(node.id)
            return
        for child in ast.iter_child_nodes(node):
            rec(child, safe)

    rec(test, False)
    return bad


@register_rule
class TracerLeakRule(Rule):
    id = "tracer-leak"
    severity = "error"
    doc = ("storing to self/globals or Python-branching on traced "
           "values inside jit/shard_map functions")

    def begin_file(self, ctx):
        # stack of (func_node, traced_param_names, global_names) for
        # jit-decorated functions currently being traversed
        self._jit_stack = []

    def visit(self, node, ctx):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                info = _jit_decoration(dec)
                if info is not None:
                    names, nums = info
                    self._jit_stack.append(
                        (node, _traced_params(node, names, nums), set()))
                    break
            return
        if not self._jit_stack:
            return
        fnode, traced, globals_ = self._jit_stack[-1]

        if isinstance(node, ast.Global):
            globals_.update(node.names)
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.ctx, ast.Store) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            ctx.report(
                self, node,
                f"assignment to self.{node.attr} inside jit-compiled "
                f"{fnode.name}() stores a tracer on a long-lived object "
                "— it escapes the trace (UnexpectedTracerError / stale "
                "compile-time state)",
                symbol=f"{fnode.name}:self.{node.attr}")
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store) \
                and node.id in globals_:
            ctx.report(
                self, node,
                f"assignment to global {node.id!r} inside jit-compiled "
                f"{fnode.name}() leaks a tracer out of the trace",
                symbol=f"{fnode.name}:global.{node.id}")
        elif isinstance(node, (ast.If, ast.While, ast.Assert)):
            test = node.test
            for name in _offending_names(test, traced):
                ctx.report(
                    self, node,
                    f"Python branch on traced argument {name!r} inside "
                    f"jit-compiled {fnode.name}() forces concretization "
                    "— use lax.cond/jnp.where, or mark the argument "
                    "static (static_argnames)",
                    symbol=f"{fnode.name}:branch.{name}")
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id in _CONCRETIZERS \
                    and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in traced:
                ctx.report(
                    self, node,
                    f"{func.id}({node.args[0].id}) inside jit-compiled "
                    f"{fnode.name}() concretizes a traced value",
                    symbol=f"{fnode.name}:{func.id}.{node.args[0].id}")
            elif isinstance(func, ast.Attribute) \
                    and func.attr in _CONCRETIZER_METHODS \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id in traced:
                ctx.report(
                    self, node,
                    f"{func.value.id}.{func.attr}() inside jit-compiled "
                    f"{fnode.name}() concretizes a traced value",
                    symbol=f"{fnode.name}:{func.attr}.{func.value.id}")

    def depart(self, node, ctx):
        if self._jit_stack and self._jit_stack[-1][0] is node:
            self._jit_stack.pop()
