"""torn-write — durable artifacts must commit atomically.

Generalizes the PR-2 checkpoint work (and its satellite fixes to
``nd.save``/``Symbol.save``/``kvstore.save_optimizer_states``): a file a
reader may open later must never be observable half-written.  The
repository pattern is

    tmp = f"{fname}.tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(...)
    os.replace(tmp, fname)

The rule flags ``open(path, 'w'/'wb'/'x'/...)`` when the enclosing
function performs no ``os.replace``/``os.rename``/``shutil.move`` —
i.e. the bytes land on the final path directly.  Near-misses that are
NOT flagged:

* the open targets a temp path (the unparsed path expression contains
  ``tmp``/``temp`` — covers writes into a ``step-NNNNNN.tmp/`` staging
  directory committed by a later directory rename);
* the function renames/replaces something (the commit is present);
* append modes (``'a'``/``'ab'``): an append-only event/record stream
  (e.g. the TensorBoard writer) tears at worst its tail record, which
  readers of those formats tolerate by design;
* ``os.fdopen`` (the fd came from ``mkstemp``-style machinery).
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

_RENAMERS = {"replace", "rename", "renames", "move"}


class _FuncRecord:
    __slots__ = ("node", "opens", "has_rename")

    def __init__(self, node):
        self.node = node
        self.opens = []         # (node, path_text)
        self.has_rename = False


def _mode_of(call):
    """The literal mode string of an ``open`` call (None if dynamic)."""
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


@register_rule
class TornWriteRule(Rule):
    id = "torn-write"
    severity = "error"
    doc = ("durable file opened for writing without the "
           "temp + os.replace commit pattern")

    def begin_file(self, ctx):
        # module scope behaves like an (outermost) function record
        self._stack = [_FuncRecord(None)]

    def visit(self, node, ctx):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._stack.append(_FuncRecord(node))
            return
        if not isinstance(node, ast.Call):
            return
        rec = self._stack[-1]
        func = node.func
        if isinstance(func, ast.Name) and func.id == "open":
            mode = _mode_of(node)
            if mode is None or not ("w" in mode or "x" in mode):
                return
            if not node.args:
                return
            path_text = ast.unparse(node.args[0]).lower()
            if "tmp" in path_text or "temp" in path_text:
                return
            rec.opens.append((node, ast.unparse(node.args[0])))
        elif isinstance(func, ast.Attribute) and func.attr in _RENAMERS:
            rec.has_rename = True

    def depart(self, node, ctx):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._flush(ctx, self._stack.pop())

    def end_file(self, ctx):
        self._flush(ctx, self._stack.pop())

    def _flush(self, ctx, rec):
        if rec.has_rename:
            return
        from ..core import Finding
        fname = rec.node.name if rec.node is not None else "<module>"
        for call, path_text in rec.opens:
            ctx.findings.append(Finding(
                self.id, self.severity, ctx.path, call.lineno,
                call.col_offset,
                f"open({path_text}, 'w') in {fname}() writes a durable "
                "file in place — a crash mid-write leaves a torn "
                "artifact; write to a '.tmp-<pid>' path and commit with "
                "os.replace (see docs/lint.md)",
                f"{fname}:{path_text}"))
