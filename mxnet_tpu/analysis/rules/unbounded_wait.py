"""unbounded-wait — joins/waits without a timeout in coordination paths.

ISSUE 11's elastic runtime is the canon: on a multi-host pod the
dominant failure mode is a peer vanishing mid-step, and every
coordination wait must prove a deadline — a ``thread.join()`` /
``Event.wait()`` / ``Condition.wait_for(pred)`` / ``future.result()``
with no timeout turns a dead peer (or a wedged worker) into a silent
hang that no watchdog dump can unwind.  The kvstore server's dead-peer
propagation and the multi-host window rendezvous exist precisely so
these waits CAN be bounded; this rule keeps new code honest.

The rule fires on an attribute call named ``join`` / ``wait`` /
``wait_for`` / ``result`` that passes **no timeout** — neither a
positional argument beyond the predicate slot nor a ``timeout=``
keyword — inside the repo's coordination modules (``parallel/``,
``kvstore*``, ``serving/``, ``chaos/``, ``checkpoint/``,
``telemetry/watchdog``).

Near-misses stay silent:

* any ``timeout`` keyword, including a **computed** one
  (``wait(timeout=deadline - now)`` — the deadline-derived idiom);
* a positional timeout (``join(5)``, ``wait(remaining)``;
  ``wait_for(pred, t)`` counts its second positional as the timeout);
* ``str.join(parts)`` / ``os.path.join(a, b)`` — ``join`` WITH
  arguments is string/path joining, not thread joining;
* code outside the coordination modules (offline tooling may block).

Deliberate unbounded waits (a writer drain whose bound is the caller's
contract, a daemon's lifetime wait) carry
``# graftlint: disable=unbounded-wait -- reason``.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

# modules where a blocked wait can strand a peer, a survivor, or a
# shutdown path (the elastic/serving/checkpoint coordination planes)
COORDINATION_PREFIXES = (
    "mxnet_tpu/parallel/",
    "mxnet_tpu/kvstore",
    "mxnet_tpu/serving/",
    "mxnet_tpu/chaos/",
    "mxnet_tpu/checkpoint/",
    "mxnet_tpu/telemetry/watchdog",
)

_WAIT_METHODS = {"join", "wait", "wait_for", "result"}


def _has_timeout(call):
    """True when the call carries any plausible bound."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    name = call.func.attr
    if name == "wait_for":
        # wait_for(predicate, timeout): second positional is the bound
        return len(call.args) >= 2
    # join(t) / wait(t) / result(t): first positional is the bound
    return len(call.args) >= 1


@register_rule
class UnboundedWaitRule(Rule):
    id = "unbounded-wait"
    severity = "warning"
    doc = ("join()/wait()/wait_for()/result() without a timeout in a "
           "coordination path — a dead peer or wedged thread becomes a "
           "silent hang; derive a deadline (docs/lint.md; the "
           "multi-host rendezvous is the template)")

    def begin_file(self, ctx):
        self._hot = any(p in ctx.path for p in COORDINATION_PREFIXES)

    def visit(self, node, ctx):
        if not self._hot:
            return
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _WAIT_METHODS):
            return
        if _has_timeout(node):
            return
        if node.func.attr == "join" and node.args:
            return  # str/path join — joining WITH args isn't a thread
        recv = ast.unparse(node.func.value)
        ctx.report(
            self, node,
            f"{recv}.{node.func.attr}() has no timeout in a "
            "coordination path — a lost peer or wedged worker turns "
            "this into a silent hang; pass a deadline-derived timeout "
            "and fail typed (PeerLostError / watchdog) instead "
            "(docs/lint.md)",
            symbol=f"{ctx.func_name()}:{node.func.attr}")
