"""double-release — a release site every path into which has already
released the same resource.

Origin: ISSUE 18's triage.  ``KVSlotPool.release`` is idempotent BY
DESIGN (chaos teardown calls it defensively), which hides the real
bug class: a second ``release()`` on every path means either dead code
(one of the two is never needed) or — worse — confused ownership where
two owners each believe they hold the slot, and the idempotence
silently absorbs what should have been a crash.  For manual
``lock.release()`` the second call raises ``RuntimeError`` at runtime;
for files a double ``close()`` is dead code that masks a missing
release of something else.

This is a MUST-analysis: the finding fires only when EVERY path
reaching the release carries a released state (the dataflow state set
at the release node is non-empty and all-R).  That is what keeps the
common guarded patterns silent:

* ``if f: f.close()`` after a conditional close — the join carries the
  unreleased branch too, so the state set is not all-R;
* release in an ``except`` handler plus release in ``finally`` when
  the handler re-raises — the finally's exception copy sees R, but
  the normal copy sees A (path-separated by the CFG's finally
  duplication), and only per-copy all-R paths fire;
* protocols that are legitimately repeatable — ``Thread.join`` and
  the keyed accumulative protocols — are excluded from the check
  entirely (``DOUBLE_RELEASE_PROTOS``).
"""
from __future__ import annotations

from ..core import GraphRule, register_graph_rule
from ..lifecycle import lifecycle_report


@register_graph_rule
class DoubleReleaseRule(GraphRule):
    id = "double-release"
    severity = "error"
    doc = ("release site reached only by paths that already released "
           "the same kv slot / trace span / file / manual lock "
           "(must-analysis: every incoming path is post-release)")

    def run(self, program):
        findings = []
        for entry in lifecycle_report(program).double_releases:
            fs = entry.fs
            findings.append(self.finding(
                fs.path, entry.lineno, entry.col,
                f"{entry.proto} resource '{entry.label}' is released "
                f"again at line {entry.lineno} in {fs.qual}() — every "
                f"path here already released it (first at line "
                f"{entry.detail['prior_line']}); one of the two is "
                "dead code or ownership is split between two owners",
                symbol=f"{fs.qual}:{entry.proto}:{entry.label}:"
                       f"L{entry.lineno}"))
        return findings
