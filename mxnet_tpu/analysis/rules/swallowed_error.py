"""swallowed-error — broad handlers that drop errors on the floor.

``except Exception:`` (or a bare ``except:``) silently swallows
``MXNetError`` — including the structured serving/checkpoint errors PR 1
and PR 2 introduced precisely so callers could react to them — and
corrupted-state bugs surface far from their cause.

A broad handler is fine when it *does something* with the error.  The
rule flags ``except Exception`` / ``except BaseException`` / bare
``except`` whose body neither

* re-raises (``raise`` anywhere in the handler), nor
* logs (a call to ``.exception()/.error()/.warning()/.debug()/...``,
  ``warnings.warn``, ``print``, ``traceback.print_exc``), nor
* uses the bound exception (``except Exception as e:`` where ``e`` is
  actually read — e.g. packed into a structured reply).

The fix is usually to narrow the type (``except ImportError:`` for an
optional dependency probe), log-and-continue for best-effort paths, or
log + re-raise where state could be corrupted.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

_BROAD = {"Exception", "BaseException"}
_LOG_CALLS = {"exception", "error", "warning", "warn", "info", "debug",
              "critical", "log", "print_exc", "format_exc"}


def _is_broad(handler):
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name) and t.id in _BROAD:
        return True
    if isinstance(t, ast.Attribute) and t.attr in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(_is_broad(ast.ExceptHandler(type=elt, name=None,
                                               body=[]))
                   for elt in t.elts)
    return False


def _handles_error(handler):
    name = handler.name
    for node in ast.walk(ast.Module(body=handler.body,
                                    type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and \
                    func.attr in _LOG_CALLS:
                return True
            if isinstance(func, ast.Name) and func.id == "print":
                return True
        if name and isinstance(node, ast.Name) and node.id == name \
                and isinstance(node.ctx, ast.Load):
            return True
    return False


@register_rule
class SwallowedErrorRule(Rule):
    id = "swallowed-error"
    severity = "warning"
    doc = ("except Exception / bare except that drops the error without "
           "re-raise, logging, or use")

    def visit(self, node, ctx):
        if not isinstance(node, ast.ExceptHandler):
            return
        if not _is_broad(node) or _handles_error(node):
            return
        shown = ("bare except" if node.type is None
                 else f"except {ast.unparse(node.type)}")
        ctx.report(
            self, node,
            f"{shown} in {ctx.func_name()}() swallows every error "
            "(including MXNetError) without re-raise, logging, or use — "
            "narrow the exception type, or log before continuing",
            symbol=f"{ctx.func_name()}:{shown}")
