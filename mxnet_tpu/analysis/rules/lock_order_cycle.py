"""lock-order-cycle — whole-program lock-acquisition ordering.

Origin: the five threaded subsystems
(serving/telemetry/checkpoint/kvstore/chaos) each own locks, and their
call graphs cross — the router routes under its pool lock into
batchers that own worker locks; the alert engine ticks under its
engine lock into the metrics registry; checkpoint hooks run into
serving.  Per-file lexical rules cannot see that thread A acquires
``X`` then ``Y`` while thread B acquires ``Y`` then ``X``: each file
looks locally disciplined, and the AB/BA deadlock only exists in the
composition.

Two prongs:

* **(a) acquisition cycles** — a global acquired-while-holding graph:
  an edge ``X -> Y`` whenever ``Y`` is acquired (directly, or by any
  transitively-called function) while ``X`` is held.  ANY cycle is an
  error: some interleaving of two threads deadlocks.  Lock identity is
  per-class (``module.Class._lock``) — every instance of a class must
  follow the same order, and instances of the SAME class are not
  distinguished (self-edges are skipped: re-entry is the
  lock-discipline rule's business, and hand-over-hand within one class
  cannot be checked statically).
* **(b) callback-under-lock** — invoking a user-supplied hook
  (``for fn in self._flip_hooks: fn(...)`` / ``hook()`` /
  ``probe()`` — an UNRESOLVABLE callable with a hook-ish name) while
  holding a lock, in the threaded subsystems.  The callee can run
  arbitrary user code: re-enter the owning object (instant deadlock on
  a non-reentrant lock) or acquire another subsystem's lock (a cycle
  edge no static analysis can see).  The repo idiom is copy-then-call:
  snapshot the hook list under the lock, invoke outside it.

Near-misses that stay silent: nested acquisition in one consistent
order everywhere (a DAG), re-entry of the same lock, hook invocation
after the copy-then-call idiom (no lock held at the call), resolvable
calls (those are walked, not guessed at), and locks acquired at
exactly ONE site in the whole program (a pure serialization latch —
the ``_tick_lock`` idiom: nothing else can be waiting on it while
holding another lock, so user code under it forms no ordering edge).
"""
from __future__ import annotations

from ..core import GraphRule, register_graph_rule
from ..summary import HOOKISH_EXACT, HOOKISH_RECEIVERS, HOOKISH_TOKENS

# modules whose classes provably run methods on multiple threads —
# prong (b) polices only these (offline tooling may call whatever it
# likes under whatever it likes)
THREADED_PREFIXES = (
    "mxnet_tpu/serving/", "mxnet_tpu/telemetry/", "mxnet_tpu/checkpoint/",
    "mxnet_tpu/chaos/", "mxnet_tpu/parallel/", "mxnet_tpu/kvstore",
)


def _hookish(call):
    name = call.parts[-1]
    if name in HOOKISH_EXACT:
        return True
    low = name.lower()
    if any(t in low for t in HOOKISH_TOKENS):
        return True
    # a method on a plugin-shaped receiver: `rule.evaluate(...)`,
    # `builder.build(...)` — the receiver name marks user-owned code
    return len(call.parts) > 1 and call.parts[0] in HOOKISH_RECEIVERS


@register_graph_rule
class LockOrderCycleRule(GraphRule):
    id = "lock-order-cycle"
    severity = "error"
    doc = ("cycle in the global acquired-while-holding lock graph, or "
           "a user hook invoked while holding a lock")

    def run(self, program):
        findings = []
        edges = {}  # (held, acquired) -> provenance dict
        # acquisition sites per lock across the program: a lock taken
        # at exactly ONE site is a pure serialization latch (the
        # `_tick_lock` idiom) — no other code path can be waiting on
        # it while holding something else, so a hook under it is not
        # an ordering edge (prong (b) near-miss)
        acq_sites = {}
        for fs in program.functions.values():
            for la in fs.lock_acquires:
                acq_sites[la.lock] = acq_sites.get(la.lock, 0) + 1
        for fs in program.functions.values():
            # direct nested acquisitions
            for la in fs.lock_acquires:
                for held in la.held:
                    self._edge(edges, held, la.lock, fs, la.lineno,
                               f"{fs.qual}() acquires {la.lock} while "
                               f"holding {held}")
            for call in fs.calls:
                if not call.held:
                    continue
                # interprocedural: callee (transitively) acquires
                if call.callee is not None:
                    for lock, (lpath, lline, chain) in \
                            program.lock_closure.get(call.callee,
                                                     {}).items():
                        for held in call.held:
                            self._edge(
                                edges, held, lock, fs, call.lineno,
                                f"{fs.qual}() holds {held} and calls "
                                + " -> ".join(f"{c}()" for c in chain)
                                + f" which acquires {lock} "
                                f"({lpath}:{lline})")
                # prong (b): unresolvable hook-ish call under a lock
                elif _hookish(call) and \
                        fs.path.startswith(THREADED_PREFIXES) and \
                        any(acq_sites.get(h, 0) >= 2 for h in call.held):
                    findings.append(self.finding(
                        fs.path, call.lineno, call.col,
                        f"{call.display}(...) is invoked while holding "
                        f"{', '.join(call.held)} in {fs.qual}() — a "
                        "user-supplied hook under a lock can re-enter "
                        "the owner or take another subsystem's lock "
                        "(deadlock/ordering edge the analyzer cannot "
                        "see); snapshot the hook list under the lock "
                        "and call OUTSIDE it",
                        symbol=f"{fs.qual}:hook.{call.parts[-1]}"))
        findings.extend(self._cycles(edges))
        return findings

    def _edge(self, edges, held, acquired, fs, lineno, desc):
        if held == acquired:
            return  # re-entry: lock-discipline's business
        edges.setdefault((held, acquired),
                         {"path": fs.path, "line": lineno,
                          "desc": desc})

    def _cycles(self, edges):
        """One finding per strongly-connected component of size >= 2
        (deterministic: reported at the lexicographically-first lock's
        outgoing edge, cycle path enumerated in the message)."""
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        sccs = _tarjan(adj)
        findings = []
        for comp in sccs:
            if len(comp) < 2:
                continue
            locks = sorted(comp)
            cycle = self._cycle_path(locks[0], set(comp), adj)
            legs = []
            for i in range(len(cycle) - 1):
                prov = edges[(cycle[i], cycle[i + 1])]
                legs.append(f"{cycle[i]} -> {cycle[i + 1]} "
                            f"({prov['path']}:{prov['line']}: "
                            f"{prov['desc']})")
            first = edges[(cycle[0], cycle[1])]
            findings.append(self.finding(
                first["path"], first["line"], 0,
                "lock-order cycle: " + "; ".join(legs) +
                " — two threads taking these in opposite order "
                "deadlock; pick ONE global order (document it) or "
                "narrow one side to copy-then-call",
                symbol="cycle:" + "->".join(locks)))
        return findings

    @staticmethod
    def _cycle_path(start, comp, adj):
        """Shortest concrete cycle through ``start`` within one SCC
        (BFS over the component's edges; deterministic)."""
        import collections
        prev = {}
        queue = collections.deque([start])
        while queue:
            cur = queue.popleft()
            for nxt in sorted(adj.get(cur, ())):
                if nxt == start and cur != start:
                    back = []
                    node = cur
                    while node != start:
                        back.append(node)
                        node = prev[node]
                    return [start] + list(reversed(back)) + [start]
                if nxt in comp and nxt not in prev and nxt != start:
                    prev[nxt] = cur
                    queue.append(nxt)
        return [start, start]


def _tarjan(adj):
    """Iterative Tarjan SCC (stdlib-free, recursion-safe)."""
    index = {}
    low = {}
    on_stack = set()
    stack = []
    sccs = []
    counter = [0]
    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)
    return sccs
