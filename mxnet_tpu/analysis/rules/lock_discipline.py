"""lock-discipline — the static race detector.

Generalizes the PR-1 shared-executor race and the PR-2 background-writer
stats races: in a class that guards state with a lock, every access to
that state must hold the lock.

Two prongs, both tuned on ``serving/executor_cache``, ``batcher``,
``repository``, ``metrics`` and ``checkpoint/manager``:

* **(a) guarded-attr escape** — an attribute written under
  ``with self._lock:`` (or any lock/condition) in one method and then
  read or written bare in another method is a race: the lock only works
  when every access site takes it.
* **(b) threaded-class bare writes** — in a class that both owns a lock
  and spawns a ``threading.Thread``/``Timer`` (so its methods provably
  run concurrently), an attribute mutated without the lock from two or
  more different methods is shared mutable state with no discipline at
  all (the ``CheckpointManager._stats`` shape).

Heuristics / known limits: any ``with``-statement over an attribute or
name containing ``lock``/``cond``/``mutex`` counts as "the lock" (locks
are not distinguished from each other); closures defined inside a
``with`` block look lock-held even though they may run later.  Accesses
in ``__init__``/``__new__``/``__del__`` are exempt (no concurrency
before construction completes / during teardown).
"""
from __future__ import annotations

import ast

from ..core import Rule, is_lockish_name, register_rule

_INIT_METHODS = ("__init__", "__new__", "__del__")

# calls that mutate their receiver in place
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "clear",
    "remove", "discard", "sort", "put", "put_nowait", "move_to_end",
}

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
_THREAD_FACTORIES = {"Thread", "Timer"}
# internally-synchronized primitives: mutating them without an extra
# lock is fine (prong (b) exemption)
_THREADSAFE_FACTORIES = {"Queue", "SimpleQueue", "LifoQueue",
                         "PriorityQueue", "Event", "Barrier"}


class _ClassRecord:
    __slots__ = ("node", "accesses", "lock_attrs", "has_lock",
                 "spawns_thread", "threadsafe_attrs")

    def __init__(self, node):
        self.node = node
        # (attr, method, locked:bool, write:bool, node)
        self.accesses = []
        self.lock_attrs = set()
        self.has_lock = False
        self.spawns_thread = False
        self.threadsafe_attrs = set()


def _self_attr(expr):
    """-> attribute name when ``expr`` is ``self.<attr>`` (else None)."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


def _unwrap_to_self_attr(target):
    """``self.x[...]...`` / ``self.x.y`` assignment target -> ``x``."""
    while isinstance(target, (ast.Subscript, ast.Attribute)):
        name = _self_attr(target)
        if name is not None:
            return name
        target = target.value
    return None


@register_rule
class LockDisciplineRule(Rule):
    id = "lock-discipline"
    severity = "error"
    doc = ("attribute guarded by a lock in one method must not be "
           "accessed bare in another")

    def begin_file(self, ctx):
        self._stack = []

    # -- collection ----------------------------------------------------------
    def visit(self, node, ctx):
        if isinstance(node, ast.ClassDef):
            self._stack.append(_ClassRecord(node))
            return
        if not self._stack or not ctx.func_stack:
            return
        rec = self._stack[-1]

        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is None:
                return
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._record(rec, attr, ctx, write, node)
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = getattr(node, "value", None)
            for t in targets:
                direct = _self_attr(t)
                if direct is not None:
                    # `self._mu = threading.Lock()` marks a lock attr
                    # even when the name doesn't look lockish;
                    # `self._q = queue.Queue()` marks a thread-safe attr
                    if isinstance(value, ast.Call):
                        vf = value.func
                        vfname = (vf.attr if isinstance(vf, ast.Attribute)
                                  else getattr(vf, "id", ""))
                        if vfname in _LOCK_FACTORIES:
                            rec.lock_attrs.add(direct)
                            rec.has_lock = True
                        elif vfname in _THREADSAFE_FACTORIES:
                            rec.threadsafe_attrs.add(direct)
                    continue  # the Attribute Store ctx records the write
                # `self.x[k] = v` / `self.x.y = v`
                attr = _unwrap_to_self_attr(t)
                if attr is not None:
                    self._record(rec, attr, ctx, True, node)
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr in _MUTATORS:
                    attr = _self_attr(func.value)
                    if attr is not None:
                        self._record(rec, attr, ctx, True, node)
                if func.attr in _THREAD_FACTORIES | {"start_new_thread"}:
                    rec.spawns_thread = True
            elif isinstance(func, ast.Name) and \
                    func.id in _THREAD_FACTORIES:
                rec.spawns_thread = True
            fname = (func.attr if isinstance(func, ast.Attribute)
                     else getattr(func, "id", ""))
            if fname in _LOCK_FACTORIES:
                rec.has_lock = True

    def _record(self, rec, attr, ctx, write, node):
        if is_lockish_name(attr):
            rec.lock_attrs.add(attr)
            rec.has_lock = True
            return
        rec.accesses.append((attr, ctx.func_name(), ctx.in_lock(),
                             write, node))

    # -- reporting -----------------------------------------------------------
    def depart(self, node, ctx):
        if not isinstance(node, ast.ClassDef) or not self._stack:
            return
        rec = self._stack.pop()
        if rec.node is not node:
            return
        cname = node.name

        protected = {a for (a, m, locked, w, _n) in rec.accesses
                     if locked and w and m not in _INIT_METHODS}
        reported = set()
        for attr, method, locked, write, anode in rec.accesses:
            if (attr in protected and not locked
                    and method not in _INIT_METHODS
                    and attr not in rec.lock_attrs):
                key = (attr, anode.lineno)
                if key in reported:
                    continue
                reported.add(key)
                ctx.findings.append(self._finding(
                    ctx, anode, cname, attr,
                    f"{cname}.{attr} is {'written' if write else 'read'} "
                    f"in {method}() without the lock, but written under "
                    "the lock elsewhere in the class — every access must "
                    "hold it (static race)"))

        if rec.has_lock and rec.spawns_thread:
            bare_write_methods = {}
            for attr, method, locked, write, anode in rec.accesses:
                if (write and not locked and attr not in protected
                        and attr not in rec.lock_attrs
                        and attr not in rec.threadsafe_attrs
                        and method not in _INIT_METHODS):
                    bare_write_methods.setdefault(attr, {})[method] = anode
            for attr, methods in sorted(bare_write_methods.items()):
                if len(methods) < 2:
                    continue
                anode = min(methods.values(), key=lambda n: n.lineno)
                ctx.findings.append(self._finding(
                    ctx, anode, cname, attr,
                    f"{cname}.{attr} is mutated without the lock from "
                    f"multiple methods ({', '.join(sorted(methods))}) of "
                    "a thread-spawning class — shared mutable state with "
                    "no lock discipline"))

    def _finding(self, ctx, node, cname, attr, message):
        from ..core import Finding
        return Finding(self.id, self.severity, ctx.path, node.lineno,
                       node.col_offset, message, f"{cname}.{attr}")
