"""naked-retry — retry/poll loops need a bound and backoff+jitter.

ISSUE 8's kvstore work is the canon: the client's RPC retry reconnects
with ``base * 2^attempt * (1 + jitter)`` sleeps under a
``MXNET_KVSTORE_RETRIES`` bound.  The anti-pattern this rule hunts is
the loop that predates that design::

    while True:
        try:
            return op()
        except Exception:
            time.sleep(1.0)        # forever, in lockstep with its peers

A naked retry has two failure modes this repo has paid for: it turns a
dead dependency into a silent hang (no attempt bound), and a fleet of
them hammers the recovering dependency in synchronized waves (constant
sleep, no jitter/backoff).

The rule fires on a ``while`` loop that (a) sleeps a **constant**
``time.sleep(c)`` in its body and (b) shows **no bound**: the loop test
contains no comparison (``while True:``, ``while not done:``) and the
body never compares a clock read (``time.time()`` / ``monotonic()`` /
``perf_counter()``) against anything — the deadline-escape idiom.

Near-misses stay silent:

* ``for attempt in range(n):`` — attempt-bounded by construction;
* ``while time.time() < deadline:`` or a ``if time.monotonic() >
  deadline: raise`` inside the body — deadline-bounded;
* ``while attempts < 5:`` — any comparison in the test counts as a
  bound;
* ``time.sleep(delay)`` where ``delay`` is computed — a non-constant
  sleep is how backoff/jitter looks in source.

Deliberate unbounded poll loops (a daemon poller whose lifetime IS the
process) carry ``# graftlint: disable=naked-retry -- reason``.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

_CLOCKS = {"time", "monotonic", "perf_counter"}


def _is_clock_call(node):
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr in _CLOCKS
    if isinstance(func, ast.Name):
        return func.id in _CLOCKS and func.id != "time"
    return False


def _is_sleep_call(node):
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr == "sleep"
    return isinstance(func, ast.Name) and func.id == "sleep"


def _const_sleep_arg(call):
    """The constant seconds of a sleep call, or None when the sleep is
    computed (backoff/jitter-shaped) or argless."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)):
        return arg.value
    # -x / +x literals
    if isinstance(arg, ast.UnaryOp) and \
            isinstance(arg.operand, ast.Constant):
        return arg.operand.value
    return None


def _contains(node, pred):
    return any(pred(n) for n in ast.walk(node))


@register_rule
class NakedRetryRule(Rule):
    id = "naked-retry"
    severity = "warning"
    doc = ("unbounded retry/poll loop sleeping a constant — add an "
           "attempt bound or deadline, and backoff+jitter "
           "(docs/chaos.md; the kvstore client retry is the template)")

    def visit(self, node, ctx):
        if not isinstance(node, ast.While):
            return
        # any comparison in the loop test is read as a bound
        # (attempt counter, deadline, queue-depth watermark...)
        if _contains(node.test, lambda n: isinstance(n, ast.Compare)):
            return
        sleeps = [n for n in ast.walk(node)
                  if _is_sleep_call(n) and _const_sleep_arg(n) is not None]
        if not sleeps:
            return
        # deadline escape anywhere in the body: a Compare whose either
        # side reads a clock
        def _deadline_compare(n):
            if not isinstance(n, ast.Compare):
                return False
            sides = [n.left] + list(n.comparators)
            return any(_contains(s, _is_clock_call) for s in sides)
        if any(_contains(stmt, _deadline_compare) for stmt in node.body):
            return
        call = sleeps[0]
        ctx.report(
            self, call,
            f"retry/poll loop sleeps a constant {_const_sleep_arg(call)}s "
            "with no attempt bound or deadline — a dead dependency "
            "becomes a silent hang and the fixed period retries in "
            "lockstep; bound the attempts and sleep "
            "base * 2^attempt * (1 + jitter) (see the kvstore client "
            "retry, docs/chaos.md)",
            symbol=f"{ctx.func_name()}:naked-retry")
