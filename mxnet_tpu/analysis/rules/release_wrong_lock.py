"""release-under-wrong-lock — acquire and paired release run under
different lock sets in a threaded subsystem.

Origin: ISSUE 18's triage of the serving KV accounting.
``KVSlotPool`` deliberately charges the ledger AFTER dropping its own
lock (PR 16: never call a metrics/accounting layer under a pool lock —
the exporter scrapes it).  A release path that slips the paired
``LEDGER.release`` back UNDER the pool lock reintroduces exactly the
lock-order hazard the design dodged, and it only deadlocks when the
exporter scrape lands mid-release — a once-a-week soak flake.  More
generally: when the acquire site of a paired protocol runs under lock
set X and the release site under a different set Y, either the acquire
leaked a lock requirement the release doesn't honor (torn state), or
the release takes locks the acquire proved unnecessary (deadlock
surface).

The lifecycle engine emits every (acquire, release) site pairing it
proved for a resource, with each site's held-lock set from the PR 15
summaries.  This rule fires only when:

* the function lives in a threaded subsystem (same
  ``THREADED_PREFIXES`` gate as lock-order-cycle — single-threaded
  tools/bench code can't deadlock), and
* the held sets DIFFER (symmetric difference non-empty).

Near-misses that stay silent: both sites lock-free, both sites under
the identical lock (the common ``with self._lock:`` pattern around
both halves), pairings where either site's held-set is unknown, and
the manual-lock protocol itself (its acquire/release ARE the lock).
"""
from __future__ import annotations

from ..core import GraphRule, register_graph_rule
from ..lifecycle import lifecycle_report
from .lock_order_cycle import THREADED_PREFIXES


@register_graph_rule
class ReleaseWrongLockRule(GraphRule):
    id = "release-under-wrong-lock"
    severity = "warning"
    doc = ("paired resource release runs under a different lock set "
           "than its acquire in a threaded subsystem (deadlock "
           "surface or torn accounting)")

    def run(self, program):
        findings = []
        seen = set()
        for entry in lifecycle_report(program).pairs:
            fs = entry.fs
            if not fs.path.startswith(THREADED_PREFIXES):
                continue
            acq_held = frozenset(entry.detail["acq_held"])
            rel_held = frozenset(entry.detail["rel_held"])
            if acq_held == rel_held:
                continue
            key = (fs.id, entry.label, entry.lineno)
            if key in seen:
                continue
            seen.add(key)
            findings.append(self.finding(
                fs.path, entry.lineno, entry.col,
                f"{entry.proto} resource '{entry.label}' is released "
                f"at line {entry.lineno} under locks "
                f"[{', '.join(sorted(rel_held)) or 'none'}] but was "
                f"acquired at line {entry.detail['acq_line']} under "
                f"[{', '.join(sorted(acq_held)) or 'none'}] in "
                f"{fs.qual}() — acquire and release must agree on "
                "their lock discipline",
                symbol=f"{fs.qual}:{entry.proto}:{entry.label}"))
        return findings
