"""leaked-thread — non-daemon threads with no bounded lifecycle in
long-running modules.

ISSUE 13's resource observatory is the canon: the host sampler counts
live threads precisely because a leaked one is invisible until shutdown
hangs or the count ratchets.  A ``threading.Thread(...)`` started in a
long-running module (``telemetry/``, ``serving/``, ``parallel/``,
``chaos/``, ``checkpoint/``) must either be ``daemon=True`` (the
process may die without it) or have a ``join(timeout=...)`` reachable
from the owner's lifecycle (a ``close()``/``stop()`` method, or the
same scope for a scoped worker pool) — otherwise a forgotten thread
pins the interpreter at exit and every restart becomes a SIGKILL.

The rule fires on a ``threading.Thread(...)`` / ``Thread(...)`` call
in a scoped module that passes no ``daemon=`` keyword AND whose storage
target (``self._thread = Thread(...)``, ``workers.append(Thread(...))``,
``ts = [Thread(...) for ...]``) is never ``.join``-ed **with a
timeout** anywhere in the file.

Near-misses stay silent:

* ``daemon=True`` (or any explicit ``daemon=`` keyword — an explicit
  decision, reviewed where made);
* worker pools with an explicit lifecycle — the created thread (or the
  list holding it, matched through ``for t in threads: t.join(5)``
  loop aliasing) is joined with a timeout somewhere in the file;
* fire-and-forget threads outside the scoped long-running modules
  (offline tooling, tests).

Deliberate unjoined non-daemon threads carry
``# graftlint: disable=leaked-thread -- reason``.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

# modules whose processes are long-running: a leaked thread here pins a
# server / trainer / launcher at exit
LONG_RUNNING_PREFIXES = (
    "mxnet_tpu/telemetry/",
    "mxnet_tpu/serving/",
    "mxnet_tpu/parallel/",
    "mxnet_tpu/chaos/",
    "mxnet_tpu/checkpoint/",
)


def _is_thread_ctor(call):
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread" and \
            isinstance(f.value, ast.Name) and f.value.id == "threading":
        return True
    return isinstance(f, ast.Name) and f.id == "Thread"


def _has_daemon_kw(call):
    return any(kw.arg == "daemon" for kw in call.keywords)


def _target_base(node):
    """Stable base name of an assignment target / receiver expression:
    ``self._thread`` -> ``_thread``, ``workers`` -> ``workers``,
    ``self._pools[k]`` -> ``_pools``."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        return _target_base(node.value)
    return None


def _join_has_timeout(call):
    return (any(kw.arg == "timeout" for kw in call.keywords)
            or len(call.args) >= 1)


@register_rule
class LeakedThreadRule(Rule):
    id = "leaked-thread"
    severity = "warning"
    doc = ("threading.Thread(...) in a long-running module without "
           "daemon=True or a join(timeout=...) reachable in the file — "
           "a leaked thread pins the interpreter at exit and hides in "
           "the thread count the resource sampler now watches "
           "(docs/lint.md)")

    def begin_file(self, ctx):
        self._hot = any(p in ctx.path for p in LONG_RUNNING_PREFIXES)
        self._candidates = []    # (node, target_name, scope)
        self._assigned = {}      # id(thread_call) -> target base name
        self._joined = set()     # base names joined WITH a timeout
        self._aliases = []       # (loop_var, iterated_base_name)

    def _thread_calls_in(self, node):
        return [n for n in ast.walk(node)
                if isinstance(n, ast.Call) and _is_thread_ctor(n)]

    def visit(self, node, ctx):
        if not self._hot:
            return
        if isinstance(node, ast.Assign):
            calls = self._thread_calls_in(node.value)
            base = _target_base(node.targets[0])
            if calls:
                for c in calls:
                    self._assigned[id(c)] = base
            elif base and isinstance(node.value, ast.Name):
                # `self._clients = clients`: a join on either name
                # bounds the other
                self._aliases.append((base, node.value.id))
                self._aliases.append((node.value.id, base))
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if isinstance(node.target, ast.Name):
                base = _target_base(node.iter)
                if base:
                    self._aliases.append((node.target.id, base))
            return
        if not isinstance(node, ast.Call):
            return
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "append":
            # workers.append(Thread(...)): the pool list is the target
            calls = self._thread_calls_in(node)
            base = _target_base(f.value)
            if calls and base:
                for c in calls:
                    self._assigned.setdefault(id(c), base)
        if isinstance(f, ast.Attribute) and f.attr == "join" and \
                _join_has_timeout(node):
            base = _target_base(f.value)
            if base:
                self._joined.add(base)
        if _is_thread_ctor(node) and not _has_daemon_kw(node):
            self._candidates.append(
                (node, self._assigned.get(id(node)), ctx.func_name()))

    def end_file(self, ctx):
        if not self._hot or not self._candidates:
            return
        joined = set(self._joined)
        # `for t in threads: t.join(5)` bounds the whole pool; chase
        # name/attr aliases to a fixpoint (loop var -> list -> attr)
        changed = True
        while changed:
            changed = False
            for var, src in self._aliases:
                if var in joined and src not in joined:
                    joined.add(src)
                    changed = True
        for node, target, scope in self._candidates:
            if target is not None and target in joined:
                continue
            what = (f"thread stored in {target!r}" if target
                    else "fire-and-forget thread")
            ctx.report(
                self, node,
                f"{what} started without daemon=True and never "
                "join(timeout=...)-ed in this file — in a long-running "
                "module a leaked non-daemon thread pins the interpreter "
                "at exit; mark it daemon or join it with a timeout from "
                "close()/stop() (docs/lint.md)",
                symbol=f"{scope}:{target or '<unnamed>'}")
