"""host-sync-in-hot-path — device→host round trips inside loops.

On TPU the killer of serving/training throughput is an unnoticed
blocking transfer: ``.asnumpy()`` / ``.asscalar()`` / ``.item()`` /
``.block_until_ready()`` inside a per-request or per-batch loop
serializes the device against the host once per iteration (the reason
PR-1's batcher stages host arrays once per *batch*, and PR-2 snapshots
device→host once per *save*).

The rule fires only inside the repo's hot paths (serving, module/model
execution, the SPMD train step) — a sync in offline tooling is fine —
and only when the call is lexically inside a ``for``/``while`` body or
a comprehension.  ``for``-loop iterables and a sync *outside* the loop
(hoisted, batched) are near-misses and stay silent.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

# modules whose loops are latency/throughput-critical
HOT_PATH_PREFIXES = (
    "mxnet_tpu/serving/",
    "mxnet_tpu/module.py",
    "mxnet_tpu/model.py",
    "mxnet_tpu/executor.py",
    "mxnet_tpu/gluon/trainer.py",
    "mxnet_tpu/parallel/spmd.py",
)

_SYNC_METHODS = {"asnumpy", "asscalar", "item", "block_until_ready"}


@register_rule
class HostSyncRule(Rule):
    id = "host-sync-in-hot-path"
    severity = "warning"
    doc = ("device->host sync (.asnumpy()/.item()/...) inside a loop in "
           "serving/train-step code")

    def begin_file(self, ctx):
        self._hot = any(p in ctx.path for p in HOT_PATH_PREFIXES)

    def visit(self, node, ctx):
        if not self._hot or not ctx.in_loop():
            return
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS):
            return
        # dict.items() etc. — `.item` is the array method, `.items` is not
        recv = ast.unparse(node.func.value)
        ctx.report(
            self, node,
            f"{recv}.{node.func.attr}() inside a loop blocks on a "
            "device->host transfer every iteration in a hot path — "
            "hoist it out of the loop or batch the transfer "
            "(one sync per batch, not per element)",
            symbol=f"{ctx.func_name()}:{node.func.attr}")
