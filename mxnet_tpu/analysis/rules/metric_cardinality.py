"""metric-cardinality — unbounded label values on hot-path metrics.

A Prometheus-style registry keys one value cell per label SET: a label
whose values come from an unbounded source (request ids, trace ids,
raw paths, URLs, exception messages) grows the registry without bound —
every scrape ships the whole history, the exporter's memory climbs
forever, and the one series an operator cares about drowns in millions
of dead ones.  ISSUE 12's tracing layer makes the temptation concrete:
``trace_id`` belongs in the exemplar store and the flight ring, NEVER
in a metric label.

The rule fires on registry metric updates — ``.inc()`` / ``.dec()`` /
``.set()`` / ``.observe()`` calls carrying a ``labels={...}`` dict —
inside the hot-path modules (serving/, parallel/, kvstore*, chaos/,
telemetry/, checkpoint/, module.py, fused_step.py, io.py) where a label
VALUE is an unbounded source:

* an identifier (name or attribute, possibly wrapped in ``str()`` /
  ``repr()`` / ``format()``) whose name carries an unbounded token:
  ``trace_id`` / ``request_id`` / ``uuid`` / ``path`` / ``filename`` /
  ``url`` / ``addr`` / ``msg`` / ``message`` / ``detail`` /
  ``traceback``;
* a live **exception variable** (``except ... as e:`` in scope) or its
  stringification — exception TEXT is unbounded; the bounded form is
  ``type(e).__name__``;
* an f-string interpolating either of the above.

Near-misses stay silent: string constants, enum-like names
(``state``/``kind``/``lane``/``site``/``action``/``op``), model and
replica names (``self.model``, ``str(rid)``), ``type(e).__name__``
(class names are a bounded set), and computed values whose identifiers
carry no unbounded token.  Deliberate exceptions carry
``# graftlint: disable=metric-cardinality -- reason``.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

HOT_PREFIXES = (
    "mxnet_tpu/serving/",
    "mxnet_tpu/parallel/",
    "mxnet_tpu/kvstore",
    "mxnet_tpu/chaos/",
    "mxnet_tpu/telemetry/",
    "mxnet_tpu/checkpoint/",
    "mxnet_tpu/module.py",
    "mxnet_tpu/fused_step.py",
    "mxnet_tpu/io.py",
)

_UPDATE_METHODS = {"inc", "dec", "set", "observe"}

# identifier substrings marking an unbounded value source
_UNBOUNDED_TOKENS = ("request_id", "trace_id", "uuid", "filename",
                     "fname", "url", "addr", "message", "msg",
                     "detail", "traceback", "path")

# wrappers that stringify without bounding the value space
_STR_WRAPPERS = {"str", "repr", "format"}


def _ident(expr):
    """The rightmost identifier of a Name/Attribute chain (or None)."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _has_token(name):
    if not name:
        return False
    low = name.lower()
    return any(tok in low for tok in _UNBOUNDED_TOKENS)


@register_rule
class MetricCardinalityRule(Rule):
    id = "metric-cardinality"
    severity = "warning"
    doc = ("metric label value drawn from an unbounded source (request/"
           "trace ids, raw paths, exception text) in a hot path — one "
           "cell per label set means the registry, the scrape and the "
           "exporter grow without bound; put per-unit identity in the "
           "trace exemplar store or the flight ring instead "
           "(docs/lint.md)")

    def begin_file(self, ctx):
        self._hot = any(p in ctx.path for p in HOT_PREFIXES)
        self._except_names = []   # stack of live `except ... as e` names

    # -- exception-variable scope tracking -----------------------------------
    def visit(self, node, ctx):
        if isinstance(node, ast.ExceptHandler):
            self._except_names.append(node.name)
        if not self._hot:
            return
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _UPDATE_METHODS):
            return
        labels = next((kw.value for kw in node.keywords
                       if kw.arg == "labels"), None)
        if not isinstance(labels, ast.Dict):
            return
        for key, value in zip(labels.keys, labels.values):
            why = self._unbounded(value)
            if why is None:
                continue
            label = (key.value if isinstance(key, ast.Constant)
                     else _ident(key) or "?")
            ctx.report(
                self, value,
                f"label {label!r} takes its value from {why} — an "
                "unbounded label source grows one registry cell per "
                "distinct value; label with a bounded enum (state/"
                "kind/model) and put per-unit identity in the trace "
                "exemplars or the flight ring (docs/lint.md)",
                symbol=f"{ctx.func_name()}:{label}")

    def depart(self, node, ctx):
        if isinstance(node, ast.ExceptHandler):
            self._except_names.pop()

    # -- value classification -------------------------------------------------
    def _is_exc_var(self, expr):
        return (isinstance(expr, ast.Name)
                and expr.id in set(filter(None, self._except_names)))

    def _unbounded(self, expr):
        """A human-readable reason when ``expr`` is an unbounded label
        source; None for the bounded near-misses."""
        # unwrap str()/repr()/format(x, ...) one level
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
                and expr.func.id in _STR_WRAPPERS and expr.args:
            inner = expr.args[0]
            if self._is_exc_var(inner):
                return f"{expr.func.id}() of a live exception variable"
            if _has_token(_ident(inner)):
                return (f"{expr.func.id}({_ident(inner)}) — an "
                        "unbounded identifier")
            return None
        # f-strings: flag when any interpolated part is unbounded
        if isinstance(expr, ast.JoinedStr):
            for part in expr.values:
                if isinstance(part, ast.FormattedValue):
                    inner = part.value
                    if self._is_exc_var(inner):
                        return "an f-string interpolating a live " \
                               "exception variable"
                    if _has_token(_ident(inner)):
                        return (f"an f-string interpolating "
                                f"{_ident(inner)!r} — an unbounded "
                                "identifier")
            return None
        if self._is_exc_var(expr):
            return "a live exception variable (unbounded message text; " \
               "use type(e).__name__)"
        # type(e).__name__ and other bounded attributes pass through the
        # token check: __name__/state/kind/... carry no unbounded token
        if _has_token(_ident(expr)):
            return f"identifier {_ident(expr)!r} — an unbounded source"
        return None
