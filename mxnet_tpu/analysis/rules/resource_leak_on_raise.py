"""resource-leak-on-raise — an acquired resource reaches the
exceptional exit of its function unreleased and untransferred.

Origin: ISSUE 18's triage of ``GenerationEngine.start_session``
(serving/generation.py).  The session trace span was started FIRST,
then ``KVSlotPool.acquire`` ran under it — on an admission-control
``RuntimeError`` (pool exhausted, queue full) the span was never
finished: every shed session leaked an open span into the tracer's
active set, and the ring buffer view showed phantom in-flight sessions
forever.  The dynamic soak harness can only catch the KV-page variant
of this AFTER it drains the pool in production-shaped traffic; the
lifecycle dataflow proves it at lint time.

The engine (``analysis/lifecycle.py``) runs a worklist dataflow over
the per-function CFG (``analysis/cfg.py``) for every resource in the
protocol table — KV-slot handles, trace spans, bare ``open()`` files,
``Thread`` handles, keyed ``LEDGER.add``/``release`` byte pairs, bare
``lock.acquire()`` outside ``with``, chaos failpoint arm/disarm.  A
finding means: on SOME exception path from after the acquire to the
function's exceptional exit there is neither a release nor an escape.

Near-misses that stay silent (the zero-false-positive discipline):

* release in a ``finally`` (the CFG inlines finally bodies on both the
  normal and the exception edge — the release covers both);
* acquisition via ``with`` (the context manager IS the release);
* the handle escapes before the raising region: returned, yielded,
  stored into an attribute, aliased, or passed to ANY callee —
  resolved releasing callees are transfers, unresolved callees are
  open-world, both silent;
* the acquire statement itself raising (its exception edge carries the
  pre-acquire state);
* keyed protocols whose acquire/release key texts differ (accumulative
  accounting like charge-new/release-evicted is not a pairing).
"""
from __future__ import annotations

from ..core import GraphRule, register_graph_rule
from ..lifecycle import lifecycle_report


@register_graph_rule
class ResourceLeakOnRaiseRule(GraphRule):
    id = "resource-leak-on-raise"
    severity = "error"
    doc = ("acquired resource (kv slot / trace span / ledger bytes / "
           "file / lock / failpoint / thread) reaches the function's "
           "exceptional exit with no release or ownership transfer on "
           "that path")

    def run(self, program):
        findings = []
        for entry in lifecycle_report(program).leaks:
            fs = entry.fs
            blame = entry.detail.get("blame_line", entry.lineno)
            via = "" if blame == entry.lineno else \
                f" when line {blame} raises"
            findings.append(self.finding(
                fs.path, entry.lineno, entry.col,
                f"{entry.proto} resource '{entry.label}' acquired at "
                f"line {entry.lineno} in {fs.qual}() can reach the "
                f"exceptional exit unreleased{via} — release it in a "
                "finally/except, use with, or hand it off before the "
                "raising region",
                symbol=f"{fs.qual}:{entry.proto}:{entry.label}"))
        return findings
