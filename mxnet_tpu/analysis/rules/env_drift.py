"""env-knob-drift — every env knob must live in the typed registry.

``mxnet_tpu/config.py`` is the single discoverable surface for the
framework's environment variables (``mx.config.describe()`` renders the
env_var.md table).  A raw ``os.environ.get("MXNET_...")`` whose name was
never ``_register``-ed is invisible to users, undocumented, untyped, and
untested — exactly how ``MXNET_COORDINATOR_URI`` and
``MXNET_MP_START_METHOD`` drifted out of the docs.

The rule statically parses the ``_register(...)`` calls out of
``config.py`` (no import — the linter stays jax-free) and flags any
literal read of a ``MXNET_*`` / ``BENCH_*`` / ``DMLC_*`` / ``MX_*``
name not in that registry, via ``os.environ.get``, ``os.getenv``, or an
``os.environ[...]`` subscript load.  Writes (``os.environ[k] = v``,
tests priming knobs) and dynamic names are not reads and stay silent.
"""
from __future__ import annotations

import ast
import os

from ..core import Rule, register_rule

_PREFIXES = ("MXNET_", "BENCH_", "DMLC_", "MX_")

_CONFIG_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "config.py")


def load_registered_names(config_path=None):
    """Names passed to ``_register(...)`` in config.py (static parse)."""
    path = config_path or _CONFIG_PATH
    names = set()
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return names
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_register"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            names.add(node.args[0].value)
    return names


def _env_read_name(node):
    """Literal env-var name read by ``node``, or None."""
    if isinstance(node, ast.Call):
        func = node.func
        # <anything>.environ.get("X") / <anything>.getenv("X")
        is_environ_get = (isinstance(func, ast.Attribute)
                          and func.attr == "get"
                          and isinstance(func.value, ast.Attribute)
                          and func.value.attr == "environ")
        is_getenv = (isinstance(func, ast.Attribute)
                     and func.attr == "getenv")
        if (is_environ_get or is_getenv) and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value
    elif isinstance(node, ast.Subscript) \
            and isinstance(node.ctx, ast.Load) \
            and isinstance(node.value, ast.Attribute) \
            and node.value.attr == "environ" \
            and isinstance(node.slice, ast.Constant) \
            and isinstance(node.slice.value, str):
        return node.slice.value
    return None


@register_rule
class EnvDriftRule(Rule):
    id = "env-knob-drift"
    severity = "warning"
    doc = ("MXNET_*/BENCH_*/DMLC_* env var read at a use site but never "
           "registered in config.py")

    def __init__(self, registered=None):
        # tests inject a registry; production parses config.py once
        self._registered = registered

    @property
    def registered(self):
        if self._registered is None:
            self._registered = load_registered_names()
        return self._registered

    def visit(self, node, ctx):
        name = _env_read_name(node)
        if name is None or not name.startswith(_PREFIXES):
            return
        if name in self.registered:
            return
        ctx.report(
            self, node,
            f"env var {name!r} is read here but not registered in "
            "mxnet_tpu/config.py — register it (type, default, doc) so "
            "config.describe() stays the complete knob surface, or "
            "delete the dead read",
            symbol=name)
