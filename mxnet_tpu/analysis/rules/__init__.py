"""graftlint rule modules — importing this package registers every rule
with the core registry (see ``core.register_rule``)."""
from . import (env_drift, host_sync, leaked_thread, lock_discipline,
               metric_cardinality, naked_retry, per_param_collective,
               phase_timing, swallowed_error, torn_write, tracer_leak,
               unbounded_wait)

__all__ = ["env_drift", "host_sync", "leaked_thread", "lock_discipline",
           "metric_cardinality", "naked_retry", "per_param_collective",
           "phase_timing", "swallowed_error", "torn_write", "tracer_leak",
           "unbounded_wait"]
