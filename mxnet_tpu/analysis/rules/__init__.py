"""graftlint rule modules — importing this package registers every rule
with the core registry (see ``core.register_rule`` /
``core.register_graph_rule``)."""
from . import (collective_divergence, double_release, env_drift,
               host_sync, leaked_thread, lock_discipline,
               lock_order_cycle, metric_cardinality, naked_retry,
               per_param_collective, phase_timing, release_wrong_lock,
               resource_leak_on_raise, swallowed_error, torn_write,
               trace_host_escape, tracer_leak, unbounded_wait)

__all__ = ["collective_divergence", "double_release", "env_drift",
           "host_sync", "leaked_thread", "lock_discipline",
           "lock_order_cycle", "metric_cardinality", "naked_retry",
           "per_param_collective", "phase_timing",
           "release_wrong_lock", "resource_leak_on_raise",
           "swallowed_error", "torn_write", "trace_host_escape",
           "tracer_leak", "unbounded_wait"]
