"""trace-host-escape — host work reachable from traced program bodies.

Origin: ISSUE 14's in-trace numerics.  The whole design of the fused/
scanned/mesh train steps is that a ``jit``/``shard_map``/``lax.scan``
body is a CLOSED device program — one dispatch per window, host control
only at the boundary.  A host-effecting call reached *through any call
chain* from the traced body breaks that silently, in one of two ways:

* it runs at TRACE time only (``time.time()``, Python RNG, metric
  ``.inc()``) — the value freezes into the compiled program, the
  side effect fires once per compile instead of once per step, and
  nobody notices until the number is wrong;
* it forces a device->host sync or materialization (``.item()``,
  ``np.asarray``, ``block_until_ready``) — a ConcretizationTypeError
  at best, a silent per-step host round-trip at worst (PyGraph makes
  the same argument for CUDA-graph capture: no host work inside the
  captured region, enforced by analysis, not convention).

The lexical ``tracer-leak`` rule sees only the decorated function's
own body.  This rule closes it over the project call graph: roots are
every traced-body registration site (``jax.jit(step)``,
``shard_map(window, ...)``, ``jax.lax.scan(body, ...)``, jit-style
decorators) and every host effect reachable from a root is reported at
the effect's site with the chain that reaches it.

Near-misses that stay silent: host effects in functions NOT reachable
from any traced root (boundary code — the whole point of the window
design), unresolvable calls (open-world: dynamic dispatch is assumed
benign rather than guessed at), and ``float()/int()`` of
non-parameter values (trace-time Python on static config).
"""
from __future__ import annotations

from ..core import GraphRule, register_graph_rule

_MAX_DEPTH = 12

_EFFECT_VERB = {
    "host_sync": "forces a device->host sync inside the traced program",
    "numpy": "materializes a host array inside the traced program "
             "(runs at trace time on tracers it will fail on; on "
             "concrete values it hides a host round-trip)",
    "clock": "reads the host clock at TRACE time — the value freezes "
             "into the compiled program",
    "metric": "updates a host-side metric at TRACE time — it fires "
              "once per compile, not once per step",
    "rng": "draws from the PYTHON rng at trace time — the draw "
           "freezes into the compiled program (use jax PRNG keys)",
    "concretize": "concretizes a (likely traced) argument",
}


@register_graph_rule
class TraceHostEscapeRule(GraphRule):
    id = "trace-host-escape"
    severity = "error"
    doc = ("host-effecting call (.item()/np.asarray/time.time/metric "
           ".inc/python rng) reachable through the call graph from a "
           "jit/shard_map/scan traced body")

    def run(self, program):
        findings = []
        reported = set()  # (path, line, col) — one finding per site
        for root in sorted(program.traced_roots, key=lambda f: f.id):
            stack = [(root, (root.name,))]
            visited = {root.id}
            while stack:
                fs, chain = stack.pop()
                for eff in fs.host_effects:
                    key = (fs.path, eff.lineno, eff.col)
                    if key in reported:
                        continue
                    reported.add(key)
                    findings.append(self._report(root, fs, chain, eff))
                if len(chain) >= _MAX_DEPTH:
                    continue
                for call in fs.calls:
                    callee = call.callee
                    if callee is None or callee in visited:
                        continue
                    visited.add(callee)
                    target = program.functions.get(callee)
                    if target is not None:
                        stack.append((target, chain + (target.name,)))
        return findings

    def _report(self, root, fs, chain, eff):
        via = "" if len(chain) == 1 else \
            " via " + " -> ".join(f"{c}()" for c in chain)
        return self.finding(
            fs.path, eff.lineno, eff.col,
            f"{eff.detail} in {fs.qual}() is reachable from the "
            f"traced body {root.name}() ({root.path}:{root.lineno})"
            f"{via} — {_EFFECT_VERB.get(eff.kind, 'host effect')}; "
            "move it to the window boundary or fold it into the "
            "traced outputs",
            symbol=f"{root.name}->{fs.name}:{eff.kind}{eff.detail}")
