"""raw-phase-timing — hot-path phase timing should be a telemetry span.

ISSUE 5 built ``mxnet_tpu.telemetry``: a ``span("fit/step/h2d")`` lands
in the chrome trace, the jax xplane trace, AND the metrics registry at
once.  Hand-rolled ``t0 = time.perf_counter()`` / ``... - t0`` deltas in
hot paths are invisible to all three — the number gets printed once and
lost, which is exactly the siloed-visibility problem the telemetry layer
exists to end.

The rule fires only on the *paired* pattern inside one function in a
hot-path module: a name assigned from a clock call
(``time.time()`` / ``time.perf_counter()`` / ``time.monotonic()``)
later SUBTRACTED — either ``clock() - t0`` or ``toc - tic`` with both
names clock-assigned.  Near-misses stay silent: deadline arithmetic
(``t0 + budget``, ``deadline - clock()``), clock reads never diffed,
and any of this outside the hot-path list.  Existing sites that ARE the
telemetry layer's own collection points carry suppressions.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

# modules where an untracked timing phase is a lost observability signal
HOT_PATH_PREFIXES = (
    "mxnet_tpu/serving/",
    "mxnet_tpu/checkpoint/",
    "mxnet_tpu/module.py",
    "mxnet_tpu/model.py",
    "mxnet_tpu/executor.py",
    "mxnet_tpu/fused_step.py",
    "mxnet_tpu/io.py",
)

_CLOCKS = {"time", "perf_counter", "monotonic"}


def _is_clock_call(node):
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute):
        # time.perf_counter() / _time.time() / xx.monotonic()
        return func.attr in _CLOCKS
    if isinstance(func, ast.Name):
        # from time import perf_counter
        return func.id in _CLOCKS and func.id != "time"
    return False


@register_rule
class PhaseTimingRule(Rule):
    id = "raw-phase-timing"
    severity = "warning"
    doc = ("hand-rolled clock-delta phase timing in a hot path — use "
           "telemetry.span so the phase lands in the trace + registry")

    def begin_file(self, ctx):
        self._hot = any(p in ctx.path for p in HOT_PATH_PREFIXES)
        self._clock_names = []  # one set per enclosing function

    def visit(self, node, ctx):
        if not self._hot:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            self._clock_names.append(set())
            return
        if not self._clock_names:
            return
        names = self._clock_names[-1]
        if isinstance(node, ast.Assign) and _is_clock_call(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
            return
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Sub)):
            return
        right_is_stamp = (isinstance(node.right, ast.Name)
                          and node.right.id in names)
        left_is_clockish = (_is_clock_call(node.left)
                            or (isinstance(node.left, ast.Name)
                                and node.left.id in names))
        if right_is_stamp and left_is_clockish:
            stamp = node.right.id
            ctx.report(
                self, node,
                f"phase timed by hand ({ast.unparse(node.left)} - {stamp}) "
                "in a hot path — wrap the region in telemetry.span(...) "
                "(or a step-timer lane) so the duration reaches the "
                "chrome trace, the xplane trace and the metrics registry "
                "instead of evaporating",
                symbol=f"{ctx.func_name()}:{stamp}")

    def depart(self, node, ctx):
        if self._hot and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if self._clock_names:
                self._clock_names.pop()
