"""collective-divergence — the SPMD-deadlock shape, whole-program.

Origin: ISSUE 11's elastic multi-host runtime.  Every rank of a
multi-process mesh runs the SAME program; a collective
(``psum``/``all_gather``/``barrier``/``window_rendezvous``/…) is a
synchronization point EVERY rank must reach in the same order.  A
branch whose condition differs per rank (``jax.process_index()``,
``self.rank``, ``mesh.local_*``) that leads — directly or through any
call chain — to a collective means some ranks arrive and some never
do: the arrivers block until the peer timeout (at best) or forever
(at worst).  The classic leader-only checkpoint bug::

    if jax.process_index() == 0:
        self._commit()          # ...which calls kv.barrier()

deadlocks the whole world even though no line of it LOOKS blocking.

Fires when a collective call is reachable under a rank-divergent
branch: either lexically inside the branch body, or via a call at a
guarded site whose callee *transitively* issues a collective
(resolved over the project call graph — the finding names the chain).
A rank-guarded early return (``if rank != 0: return``) marks the rest
of the function divergent fallthrough and is reported the same way.

Near-misses that stay silent: leader-only work AFTER an unconditional
barrier (the barrier is not under the guard), rank-guarded
logging/metrics-only branches (no collective reachable — unresolvable
calls are assumed benign, open-world), and uniform conditions
(``world_size``, step counters) that every rank computes identically.
"""
from __future__ import annotations

from ..core import GraphRule, register_graph_rule


def _chain_text(chain):
    return " -> ".join(f"{name}()" for name in chain)


@register_graph_rule
class CollectiveDivergenceRule(GraphRule):
    id = "collective-divergence"
    severity = "error"
    doc = ("collective (psum/all_gather/barrier/rendezvous) reachable "
           "under a rank-divergent branch — the SPMD deadlock shape")

    def run(self, program):
        findings = []
        for fs in program.functions.values():
            for coll in fs.collectives:
                if coll.guard is None:
                    continue
                findings.append(self._report(fs, coll.lineno, coll.col,
                                             coll.guard, coll.kind,
                                             fs.path, coll.lineno,
                                             (fs.name,)))
            for call in fs.calls:
                if call.guard is None or call.callee is None:
                    continue
                hit = program.collective_closure.get(call.callee)
                if hit is None:
                    continue
                kind, cpath, cline, chain = hit
                findings.append(self._report(
                    fs, call.lineno, call.col, call.guard, kind,
                    cpath, cline, (fs.name,) + chain))
        return findings

    def _report(self, fs, line, col, guard, kind, cpath, cline, chain):
        where = "the rest of the function after the rank-guarded " \
                "return" if guard.via_return else "a rank-divergent " \
                "branch"
        via = "" if len(chain) == 1 else \
            f" via {_chain_text(chain)}"
        return self.finding(
            fs.path, line, col,
            f"collective {kind}() ({cpath}:{cline}) is reachable "
            f"under {where} (condition `{guard.cond}` at line "
            f"{guard.lineno}){via} — ranks that skip the branch never "
            "arrive and the mesh deadlocks; hoist the collective out "
            "of the guard or make every rank take it",
            symbol=f"{fs.qual}:{kind}.{chain[-1]}")
