"""per-param-collective — per-parameter collective/transfer loops on
distributed hot paths.

ISSUE 9's mesh fused step is the canon: gradient synchronization runs
as ONE ``psum``/``reduce_scatter`` per ``MXNET_COLLECTIVE_BUCKET_MB``-
sized flat bucket *inside* the donated train-step program.  The
anti-pattern this rule hunts is the loop that design retired::

    for name in param_names:
        kvstore.push(name, grads[name])      # one host round-trip
        kvstore.pull(name, weights[name])    # ... per PARAMETER

163 tiny transfers per ResNet-50 step serialize the host against the
store/device once per parameter; bucketed/batched forms amortize them
into a handful of large ones that XLA (or the wire) can pipeline.

The rule fires when a ``push``/``pull``/``pushpull``/``psum``/
``device_put``/``all_gather``/``ppermute`` call sits lexically inside a
``for``/``while`` body (or comprehension) in the distributed hot paths
(``parallel/``, ``kvstore*.py``, ``module.py``, ``model.py``).

Near-misses stay silent:

* batched forms — ``push_many`` / ``pull_many`` / ``init_many`` /
  ``bucketed_all_reduce`` move many tensors per call by construction;
* init-time loops — an enclosing function whose name mentions init /
  broadcast / attach / restore / load / state runs once per
  bind/resume, not once per step;
* calls outside any loop — a single collective per step is the goal.

Residual per-param paths kept deliberately (the loop the mesh step
falls back to for ineligible setups) carry
``# graftlint: disable=per-param-collective -- reason``.
"""
from __future__ import annotations

import ast

from ..core import Rule, register_rule

# distributed hot paths: per-step collective loops here tax every step
HOT_PATH_PREFIXES = (
    "mxnet_tpu/parallel/",
    "mxnet_tpu/kvstore",
    "mxnet_tpu/module.py",
    "mxnet_tpu/model.py",
)

# one tensor per call: the shapes the per-param loop is made of
_COLLECTIVE_ATTRS = {"push", "pull", "pushpull", "psum", "psum_scatter",
                     "device_put", "all_gather", "reduce_scatter",
                     "ppermute"}
# many tensors per call: the batched/bucketed near-miss forms
_BATCHED_ATTRS = {"push_many", "pull_many", "init_many",
                  "bucketed_all_reduce", "fsdp_bucket_update"}

# an enclosing function with one of these tokens is setup, not hot path
_INIT_TOKENS = ("init", "broadcast", "attach", "restore", "load",
                "state", "checkpoint", "calibrate")


@register_rule
class PerParamCollectiveRule(Rule):
    id = "per-param-collective"
    severity = "warning"
    doc = ("per-parameter push/pull/psum/device_put loop on a "
           "distributed hot path — bucket or batch the transfers "
           "(docs/parallel.md; the mesh fused step's flat buckets are "
           "the template)")

    def begin_file(self, ctx):
        self._hot = any(p in ctx.path for p in HOT_PATH_PREFIXES)

    def visit(self, node, ctx):
        if not self._hot or not ctx.in_loop():
            return
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            return
        attr = node.func.attr
        if attr in _BATCHED_ATTRS or attr not in _COLLECTIVE_ATTRS:
            return
        fname = ctx.func_name().lower()
        if any(tok in fname for tok in _INIT_TOKENS):
            return  # init/resume-time loop: once per bind, not per step
        recv = ast.unparse(node.func.value)
        ctx.report(
            self, node,
            f"{recv}.{attr}() inside a loop issues one collective/"
            "transfer per iteration on a distributed hot path — "
            "163 per-param round-trips is the tax the mesh fused step "
            "retired; flatten the tensors into "
            "MXNET_COLLECTIVE_BUCKET_MB-sized buckets (parallel/"
            "fused.bucketed_all_reduce) or use the *_many batched "
            "forms",
            symbol=f"{ctx.func_name()}:{attr}")
