"""graftlint phase 1.5 — per-function control-flow graphs.

The v2 summaries are path-*insensitive*: they record that a function
calls ``release()`` but not that the release is skipped when the code
between ``acquire()`` and ``release()`` raises.  This module builds the
statement-level CFG the lifecycle dataflow (``analysis/lifecycle.py``)
runs over:

* one node per simple statement, plus one *header* node per compound
  statement (the ``if``/``while`` test, the ``for`` iterable, the
  ``with`` items) — bodies are lowered recursively;
* three virtual nodes: ENTRY, EXIT (normal return) and RAISE (the
  exceptional exit — "an exception escapes this function");
* **implicit exception edges out of every call site**: any statement
  whose header expressions contain a call (or ``raise``/``assert``/
  ``await``/``yield``) gets an ``exception`` edge to the innermost
  enclosing handler set, or to RAISE;
* ``try/except/else/finally`` with real Python semantics: body
  exceptions edge to every handler entry (and past them when no
  handler is a catch-all), ``else`` runs outside the handler
  protection, and exceptions raised *inside* a handler propagate
  outward (never to a sibling handler);
* ``finally`` bodies are **inlined by duplication** — one memoized
  exception copy per ``try`` (all raisers share the same continuation:
  propagate outward), one normal copy, and a fresh copy per
  ``return``/``break``/``continue`` that crosses the ``finally`` — so
  a release inside ``finally`` is seen on every path it actually runs
  on, including the exceptional one;
* ``break``/``continue`` route through every intervening ``finally``
  to the loop exit / loop header; ``while``/``for`` ``else`` clauses
  hang off the exhausted edge (a ``break`` bypasses them);
* ``with`` is exception-transparent (the common case), except
  ``contextlib.suppress(...)`` / ``pytest.raises(...)`` items, which
  catch the body's exceptions and continue after the block.

Node duplication is bounded: a function whose lowering exceeds
``MAX_NODES`` gets a CFG marked ``capped`` and the lifecycle analysis
skips it (missing-a-finding is acceptable; a wrong finding is not).

Stdlib-``ast`` only, like the rest of the package.
"""
from __future__ import annotations

import ast

NORMAL = "normal"
EXCEPTION = "exception"

MAX_NODES = 4000

_CATCHALL_NAMES = ("Exception", "BaseException")
_SUPPRESSING_WITH_TAILS = ("suppress", "raises")


def _tail(expr):
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return ""


class Node:
    """One CFG node.  ``stmt`` is the governing AST statement (or the
    ``ast.excepthandler`` for handler entries; ``None`` for virtual
    nodes); ``kind`` labels the role; ``succs`` is a list of
    ``(node_index, edge_kind)`` with edge_kind ``normal``/``exception``."""

    __slots__ = ("idx", "stmt", "kind", "succs")

    def __init__(self, idx, stmt, kind):
        self.idx = idx
        self.stmt = stmt
        self.kind = kind
        self.succs = []

    def add_succ(self, idx, kind=NORMAL):
        edge = (idx, kind)
        if edge not in self.succs:
            self.succs.append(edge)

    @property
    def lineno(self):
        return getattr(self.stmt, "lineno", 0)

    def __repr__(self):
        where = f"@{self.lineno}" if self.stmt is not None else ""
        return f"Node({self.idx}, {self.kind}{where})"


class CFG:
    """The built graph: ``nodes[0]`` is ENTRY, ``nodes[cfg.exit]`` the
    normal exit, ``nodes[cfg.raise_exit]`` the exceptional exit."""

    __slots__ = ("nodes", "entry", "exit", "raise_exit", "capped")

    def __init__(self):
        self.nodes = []
        self.entry = 0
        self.exit = 0
        self.raise_exit = 0
        self.capped = False

    # -- introspection helpers (tests, debugging) ----------------------------
    def nodes_at(self, lineno):
        return [n for n in self.nodes
                if n.stmt is not None and n.lineno == lineno]

    def edges(self, kind=None):
        out = []
        for n in self.nodes:
            for idx, k in n.succs:
                if kind is None or k == kind:
                    out.append((n.idx, idx, k))
        return out

    def preds(self):
        """{node idx: [(pred idx, edge kind)]}."""
        pred = {n.idx: [] for n in self.nodes}
        for n in self.nodes:
            for idx, k in n.succs:
                pred[idx].append((n.idx, k))
        return pred


# -- statement classification -------------------------------------------------
def header_exprs(stmt):
    """The expressions a statement's own CFG node evaluates (compound
    statements contribute only their header — bodies are separate
    nodes)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Return):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, ast.Assert):
        return [e for e in (stmt.test, stmt.msg) if e is not None]
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, ast.Assign):
        return [stmt.value] + list(stmt.targets)
    if isinstance(stmt, ast.AugAssign):
        return [stmt.value, stmt.target]
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.value] if stmt.value is not None else []
    if isinstance(stmt, ast.Delete):
        return list(stmt.targets)
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return list(stmt.decorator_list)
    if isinstance(stmt, ast.ClassDef):
        return list(stmt.decorator_list) + list(stmt.bases)
    return []


def _contains_call(exprs):
    for expr in exprs:
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Call, ast.Await, ast.Yield,
                                 ast.YieldFrom)):
                return True
            if isinstance(node, ast.Lambda):
                continue            # body runs later, not here
            stack.extend(ast.iter_child_nodes(node))
    return False


def can_raise(stmt):
    """Whether this statement's own evaluation gets an implicit
    exception edge.  Policy: explicit ``raise``/``assert`` always;
    otherwise only statements whose header contains a call site —
    attribute reads, arithmetic, subscripts stay edge-free (fewer
    spurious paths keeps the lifecycle rules at zero false
    positives)."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    return _contains_call(header_exprs(stmt))


def _is_catchall(handler):
    if handler.type is None:
        return True
    if isinstance(handler.type, ast.Tuple):
        return any(_tail(e) in _CATCHALL_NAMES for e in handler.type.elts)
    return _tail(handler.type) in _CATCHALL_NAMES


def _is_suppressing_with(stmt):
    for item in stmt.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call) and \
                _tail(expr.func) in _SUPPRESSING_WITH_TAILS:
            return True
    return False


class _Capped(Exception):
    pass


# -- the builder --------------------------------------------------------------
class _Builder:
    """Lowers one function body.  ``frames`` is the exception-routing
    stack, innermost last; entries are

    * ``("handlers", [entry idx, ...], catchall)`` — an active
      ``except`` clause set,
    * ``("finally", finalbody stmts, memo dict)`` — a ``finally``
      exceptions must run through (memo holds the shared exception
      copy),
    * ``("loop", info dict)`` — a loop for ``break``/``continue``
      targeting (transparent to exception routing).
    """

    def __init__(self):
        self.cfg = CFG()
        self.frames = []

    def build(self, func):
        cfg = self.cfg
        entry = self._new(None, "entry")
        cfg.entry = entry.idx
        exit_node = self._new(None, "exit")
        cfg.exit = exit_node.idx
        raise_node = self._new(None, "raise")
        cfg.raise_exit = raise_node.idx
        try:
            exits = self._lower_block(func.body, [entry.idx])
            for idx in exits:
                self._edge(idx, cfg.exit)
        except _Capped:
            cfg.capped = True
        return cfg

    # -- plumbing ------------------------------------------------------------
    def _new(self, stmt, kind):
        if len(self.cfg.nodes) >= MAX_NODES:
            raise _Capped
        node = Node(len(self.cfg.nodes), stmt, kind)
        self.cfg.nodes.append(node)
        return node

    def _edge(self, src, dst, kind=NORMAL):
        self.cfg.nodes[src].add_succ(dst, kind)

    def _exc_edges(self, src, frames=None):
        """Route an exception raised at ``src`` per the frame stack:
        into every live handler entry, through the memoized exception
        copy of each intervening ``finally``, and finally to RAISE."""
        if frames is None:
            frames = self.frames
        i = len(frames) - 1
        while i >= 0:
            tag = frames[i][0]
            if tag == "handlers":
                _tag, entries, catchall = frames[i]
                for e in entries:
                    self._edge(src, e, EXCEPTION)
                if catchall:
                    return
            elif tag == "finally":
                entry = self._finally_exc_copy(frames[i], frames[:i])
                self._edge(src, entry, EXCEPTION)
                return
            i -= 1
        self._edge(src, self.cfg.raise_exit, EXCEPTION)

    def _finally_exc_copy(self, frame, outer_frames):
        """The (memoized) exception copy of a ``finally`` body: runs
        the body, then re-raises through the *outer* frames."""
        memo = frame[2]
        if "exc" not in memo:
            anchor, exits = self._copy_finally(frame, outer_frames)
            reraise = self._new(None, "reraise")
            for idx in exits:
                self._edge(idx, reraise.idx)
            self._exc_edges(reraise.idx, outer_frames)
            memo["exc"] = anchor
        return memo["exc"]

    def _copy_finally(self, frame, outer_frames):
        """Lower one fresh copy of a finally body under the outer
        frame stack; -> (anchor idx, normal-exit idxs)."""
        saved = self.frames
        self.frames = list(outer_frames)
        try:
            anchor = self._new(None, "finally")
            exits = self._lower_block(frame[1], [anchor.idx])
        finally:
            self.frames = saved
        return anchor.idx, exits

    def _route_through_finallys(self, start_idxs, down_to=None):
        """Chain ``start_idxs`` through a fresh copy of every finally
        frame above ``down_to`` (a frame index; None = all the way
        out), innermost first; -> the final exit idxs."""
        ends = list(start_idxs)
        i = len(self.frames) - 1
        floor = -1 if down_to is None else down_to
        while i > floor:
            frame = self.frames[i]
            if frame[0] == "finally":
                anchor, exits = self._copy_finally(frame, self.frames[:i])
                for idx in ends:
                    self._edge(idx, anchor)
                ends = exits
            i -= 1
        return ends

    def _nearest_loop(self):
        for i in range(len(self.frames) - 1, -1, -1):
            if self.frames[i][0] == "loop":
                return i, self.frames[i][1]
        return None, None

    # -- lowering ------------------------------------------------------------
    def _lower_block(self, stmts, preds):
        exits = list(preds)
        for stmt in stmts:
            exits = self._lower_stmt(stmt, exits)
        return exits

    def _simple(self, stmt, preds, kind="stmt"):
        node = self._new(stmt, kind)
        for p in preds:
            self._edge(p, node.idx)
        if can_raise(stmt):
            self._exc_edges(node.idx)
        return node

    def _lower_stmt(self, stmt, preds):
        if isinstance(stmt, ast.If):
            test = self._simple(stmt, preds, "if")
            body_exits = self._lower_block(stmt.body, [test.idx])
            if stmt.orelse:
                else_exits = self._lower_block(stmt.orelse, [test.idx])
            else:
                else_exits = [test.idx]
            return body_exits + else_exits

        if isinstance(stmt, ast.While):
            test = self._simple(stmt, preds, "while")
            always = isinstance(stmt.test, ast.Constant) and \
                bool(stmt.test.value)
            info = {"breaks": [], "header": test.idx}
            self.frames.append(("loop", info))
            body_exits = self._lower_block(stmt.body, [test.idx])
            self.frames.pop()
            for idx in body_exits:
                self._edge(idx, test.idx)
            exits = list(info["breaks"])
            if not always:
                if stmt.orelse:
                    exits += self._lower_block(stmt.orelse, [test.idx])
                else:
                    exits.append(test.idx)
            return exits

        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            header = self._simple(stmt, preds, "for")
            info = {"breaks": [], "header": header.idx}
            self.frames.append(("loop", info))
            body_exits = self._lower_block(stmt.body, [header.idx])
            self.frames.pop()
            for idx in body_exits:
                self._edge(idx, header.idx)
            exits = list(info["breaks"])
            if stmt.orelse:
                exits += self._lower_block(stmt.orelse, [header.idx])
            else:
                exits.append(header.idx)
            return exits

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            header = self._simple(stmt, preds, "with")
            if _is_suppressing_with(stmt):
                join = self._new(None, "with-exit")
                self.frames.append(("handlers", [join.idx], True))
                body_exits = self._lower_block(stmt.body, [header.idx])
                self.frames.pop()
                for idx in body_exits:
                    self._edge(idx, join.idx)
                return [join.idx]
            return self._lower_block(stmt.body, [header.idx])

        if isinstance(stmt, ast.Try) or (hasattr(ast, "TryStar")
                                         and isinstance(stmt, ast.TryStar)):
            return self._lower_try(stmt, preds)

        if isinstance(stmt, ast.Return):
            node = self._simple(stmt, preds, "return")
            ends = self._route_through_finallys([node.idx])
            for idx in ends:
                self._edge(idx, self.cfg.exit)
            return []

        if isinstance(stmt, ast.Raise):
            node = self._new(stmt, "raise")
            for p in preds:
                self._edge(p, node.idx)
            self._exc_edges(node.idx)
            return []

        if isinstance(stmt, ast.Break):
            node = self._simple(stmt, preds, "break")
            li, info = self._nearest_loop()
            if info is None:          # malformed; treat as fallthrough
                return [node.idx]
            ends = self._route_through_finallys([node.idx], down_to=li)
            info["breaks"].extend(ends)
            return []

        if isinstance(stmt, ast.Continue):
            node = self._simple(stmt, preds, "continue")
            li, info = self._nearest_loop()
            if info is None:
                return [node.idx]
            ends = self._route_through_finallys([node.idx], down_to=li)
            for idx in ends:
                self._edge(idx, info["header"])
            return []

        if isinstance(stmt, ast.Match):
            subject = self._simple(stmt, preds, "match")
            exits = [subject.idx]     # no case may match
            for case in stmt.cases:
                exits += self._lower_block(case.body, [subject.idx])
            return exits

        # simple statements (incl. nested def/class: their bodies run
        # later and belong to their own CFGs)
        node = self._simple(stmt, preds)
        return [node.idx]

    def _lower_try(self, stmt, preds):
        has_finally = bool(stmt.finalbody)
        if has_finally:
            fin_frame = ("finally", stmt.finalbody, {})
            self.frames.append(fin_frame)

        handler_entries = []
        for handler in stmt.handlers:
            handler_entries.append(self._new(handler, "except").idx)
        catchall = any(_is_catchall(h) for h in stmt.handlers)

        if stmt.handlers:
            self.frames.append(("handlers", handler_entries, catchall))
        body_exits = self._lower_block(stmt.body, preds)
        if stmt.handlers:
            self.frames.pop()

        # else runs after normal body completion, OUTSIDE the handlers
        if stmt.orelse:
            else_exits = self._lower_block(stmt.orelse, body_exits)
        else:
            else_exits = body_exits

        handler_exits = []
        for handler, entry in zip(stmt.handlers, handler_entries):
            handler_exits += self._lower_block(handler.body, [entry])

        normal_in = else_exits + handler_exits
        if has_finally:
            self.frames.pop()
            if not normal_in:         # every path returned/raised
                return []
            anchor, exits = self._copy_finally(fin_frame, self.frames)
            for idx in normal_in:
                self._edge(idx, anchor)
            return exits
        return normal_in


def build_cfg(func):
    """CFG for one ``ast.FunctionDef``/``AsyncFunctionDef``.  Returns
    a :class:`CFG`; check ``.capped`` before trusting it."""
    return _Builder().build(func)
