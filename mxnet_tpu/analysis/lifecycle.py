"""graftlint phase 2.5 — path-sensitive resource-lifecycle dataflow.

The codebase runs on paired-resource protocols everywhere: ``LEDGER.add
/release`` byte accounting, ``KVSlotPool`` acquire/release, trace-span
``start``/``finish``, chaos failpoint ``arm``/``disarm``, manual
``Lock.acquire``/``release`` outside ``with``, bare ``open``/``close``,
``Thread.start``/``join``.  A release that is skipped when the code
between acquire and release raises is an HBM/accounting leak the alert
engine only sees *after* it happens in production.  This module proves
the pairing at lint time: a worklist dataflow over the per-function CFG
(``analysis/cfg.py``) tracks each resource through an abstract state
lattice and reports where an acquired resource reaches the exceptional
exit unreleased.

**States** (per resource, per program point — a SET of tagged states,
so a join keeps both sides):

* ``U`` — not (yet) acquired on this path;
* ``A(line)`` — acquired at ``line``, live;
* ``R(line)`` — released at ``line``;
* ``E(line)`` — escaped at ``line``: ownership transferred out.

**Transfer rules** (the zero-false-positive discipline):

* an *acquire* statement's own exception edge carries the PRE-state
  (if ``pool.acquire()`` itself raises, nothing was acquired);
* a *release* or *escape* statement's exception edge carries the
  POST-state (if ``release()`` raises, we still credit the release —
  claiming a leak there would be speculative);
* *escape* is any of: the handle returned or yielded; stored into an
  attribute/subscript; aliased to another name; or passed as an
  argument to ANY call.  A callee whose summary provably releases its
  parameter (directly or transitively over the resolved call graph)
  classifies the escape as a *transfer*; an unresolved callee stays
  open-world — **both are silent**, the classification is reported for
  introspection only.  Reads stay benign: method calls *on* the handle
  (``h.stage(...)``) and bare-name/truthiness tests (``if h:``,
  ``assert h``) do not escape.

**Protocols** come in two shapes:

* *handle* protocols (``h = pool.acquire(...)`` … ``pool.release(h)``
  / ``h.finish()``): the resource is a local name; escape analysis
  applies.  Tracked only when the function also contains a matching
  release — or, for protocols where a dangling resource is a real bug
  even when handed off (kv slots, trace spans, files), an escape.
* *keyed* protocols (``LEDGER.add(owner, kind, n)``): no handle to
  track, so the pairing is textual — tracked only when ONE function
  contains both the acquire and a release with the IDENTICAL key text
  (for the ledger that includes the amount expression: charge-N /
  release-N is a pairing, charge-new/release-evicted is accumulative
  accounting and stays silent).

``with``-item acquisitions are never tracked (the ``with`` releases).
Functions whose CFG lowering exceeds the node cap are skipped.

Three graph rules consume one memoized report per program:
``resource-leak-on-raise``, ``double-release`` (must-analysis: flagged
only when EVERY path into a release has already released), and
``release-under-wrong-lock`` (held-set mismatch between the paired
acquire/release sites, threaded subsystems only — the rule filters).
"""
from __future__ import annotations

import ast
from collections import deque

from .cfg import EXCEPTION, build_cfg, header_exprs
from .core import is_lockish_name

# -- protocol table -----------------------------------------------------------
#: handle protocols: methods ON the handle that release it
HANDLE_RELEASE_METHODS = {
    "kv-slot": ("release", "free"),
    "trace-span": ("finish",),
    "file": ("close",),
    "thread": ("join",),
}

#: protocols where a second release on an already-released path is a
#: definite bug (locks raise RuntimeError; slot/span/file double
#: release is dead or confused code) — thread.join and the accumulative
#: keyed protocols are legitimately repeatable
DOUBLE_RELEASE_PROTOS = {"kv-slot", "trace-span", "file", "lock-manual"}

#: handle protocols tracked even without a local release, when the
#: handle escapes: an exception BEFORE the hand-off dangles a resource
#: whose owner never existed (thread handles are excluded — a started
#: thread without a local join is the leaked-thread rule's business)
TRACK_ON_ESCAPE = {"kv-slot", "trace-span", "file"}

#: keyed protocols eligible for the wrong-lock pairing check are
#: everything except the locks themselves
WRONG_LOCK_EXEMPT = {"lock-manual"}

_CHAOS_PATHS = ("tests/", "mxnet_tpu/chaos/")

_MAX_KEY = 60
_FIXPOINT_ROUNDS = 4


def _dotted(expr):
    """Cheap dotted text for a receiver chain (``self._pool``,
    ``_ledger()``); None when not name-shaped."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    if isinstance(expr, ast.Call):
        inner = _dotted(expr.func)
        return None if inner is None else inner + "()"
    return None


def _key_text(expr):
    try:
        text = ast.unparse(expr)
    except (ValueError, RecursionError):
        text = "<expr>"
    return text


def _root_name(expr):
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class Event:
    """One protocol event at one call site."""

    __slots__ = ("op", "proto", "res", "lineno", "col", "esc_kind",
                 "call_pos")

    def __init__(self, op, proto, res, lineno, col, esc_kind=None,
                 call_pos=None):
        self.op = op              # acquire | release | escape
        self.proto = proto
        self.res = res            # "h:<name>" or "k:<proto>:<key>"
        self.lineno = lineno
        self.col = col            # call col_offset (held-set lookup)
        self.esc_kind = esc_kind  # return/store/alias/arg/bare/...
        self.call_pos = call_pos  # escape-to-arg: ((line, col), index)

    def __repr__(self):
        return f"Event({self.op}, {self.res}@{self.lineno})"


# -- statement iteration ------------------------------------------------------
_NESTED = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
           ast.Lambda)


def iter_own_statements(func):
    """Every statement executed by ``func``'s own frame (nested
    def/class bodies run later and belong to their own summaries)."""
    stack = list(reversed(func.body))
    while stack:
        stmt = stack.pop()
        yield stmt
        if isinstance(stmt, _NESTED):
            continue
        for field in ("body", "orelse", "finalbody"):
            stack.extend(reversed(getattr(stmt, field, []) or []))
        for handler in getattr(stmt, "handlers", []) or []:
            stack.extend(reversed(handler.body))
        for case in getattr(stmt, "cases", []) or []:
            stack.extend(reversed(case.body))


def _calls_in(exprs):
    for expr in exprs:
        if expr is None:
            continue
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Call):
                yield node
            stack.extend(ast.iter_child_nodes(node))


# -- event extraction ---------------------------------------------------------
class _Extractor:
    """One pass over a function's statements, in source (= execution)
    order, producing events keyed by statement identity (finally
    copies in the CFG share statement objects, so a release inside
    ``finally`` is seen on every path the copy runs on)."""

    def __init__(self, path):
        self.path = path
        self.events = {}          # id(stmt) -> [Event]
        self.handles = {}         # local name -> proto
        self.thread_decls = set()
        self.keyed_seen = {}      # res -> {"acquire": n, "release": n}
        self.consumed = set()     # (id(call), handle) release args

    def run(self, func):
        for stmt in iter_own_statements(func):
            evs = self._statement_events(stmt)
            if evs:
                self.events[id(stmt)] = evs
        return self

    def _statement_events(self, stmt):
        evs = []
        in_with = isinstance(stmt, (ast.With, ast.AsyncWith))
        # handle declaration (simple local assignment from an acquire)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name) and \
                isinstance(stmt.value, ast.Call):
            name = stmt.targets[0].id
            proto = self._acquire_proto(stmt.value)
            if proto == "thread":
                self.thread_decls.add(name)
                self.handles[name] = "thread"
            elif proto is not None:
                self.handles[name] = proto
                evs.append(Event("acquire", proto, f"h:{name}",
                                 stmt.value.lineno,
                                 stmt.value.col_offset))
        # keyed events + handle releases, in every header expression
        for call in _calls_in(header_exprs(stmt)):
            evs.extend(self._classify_call(call, stmt,
                                           in_with_items=in_with))
        # escapes of known handles
        if self.handles:
            evs.extend(self._escape_events(stmt))
        evs.sort(key=lambda e: (e.lineno, e.col))
        return evs

    @staticmethod
    def _acquire_proto(call):
        func = call.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "file"
            if func.id == "Thread":
                return "thread"
            return None
        if isinstance(func, ast.Attribute):
            if func.attr == "Thread":
                return "thread"
            recv = _dotted(func.value)
            low = recv.lower() if recv else ""
            if func.attr in ("acquire", "lease") and "pool" in low:
                return "kv-slot"
            if func.attr == "start" and "trace" in low:
                return "trace-span"
            if func.attr == "begin_span":
                return "trace-span"
        return None

    def _classify_call(self, call, stmt, in_with_items):
        func = call.func
        evs = []
        if isinstance(func, ast.Attribute):
            recv = _dotted(func.value)
            low = recv.lower() if recv else ""
            tail = func.attr
            # ledger bytes: keyed on (owner, kind, amount) text
            if "ledger" in low and tail in ("add", "release") and \
                    len(call.args) >= 3 and not in_with_items:
                key = "|".join(_key_text(a) for a in call.args[:3])
                evs.append(self._keyed(
                    "acquire" if tail == "add" else "release",
                    "ledger-bytes", key, call))
                return evs
            # chaos failpoints: keyed on the site argument
            if tail in ("arm", "disarm") and call.args and \
                    ("chaos" in low or "failpoint" in low):
                evs.extend(self._chaos(tail, call))
                return evs
            # manual lock acquire/release OUTSIDE with: only the bare
            # blocking Expr-statement form (`ok = l.acquire(False)` is
            # value-dependent — near-miss)
            if tail in ("acquire", "release") and not call.args and \
                    not call.keywords and recv is not None and \
                    "pool" not in low and \
                    is_lockish_name(recv.rsplit(".", 1)[-1]) and \
                    isinstance(stmt, ast.Expr) and stmt.value is call:
                evs.append(self._keyed(tail, "lock-manual", recv, call))
                return evs
            # pool.release(h): handle released by argument
            if tail in ("release", "free") and "pool" in low and \
                    len(call.args) == 1 and \
                    isinstance(call.args[0], ast.Name) and \
                    call.args[0].id in self.handles:
                name = call.args[0].id
                self.consumed.add((id(call), name))
                evs.append(Event("release", self.handles[name],
                                 f"h:{name}", call.lineno,
                                 call.col_offset))
                return evs
            # h.finish()/h.close()/h.release()/h.join(): method release
            if isinstance(func.value, ast.Name) and \
                    func.value.id in self.handles:
                name = func.value.id
                proto = self.handles[name]
                if tail in HANDLE_RELEASE_METHODS.get(proto, ()):
                    evs.append(Event("release", proto, f"h:{name}",
                                     call.lineno, call.col_offset))
                    return evs
                if proto == "thread" and tail == "start":
                    evs.append(Event("acquire", proto, f"h:{name}",
                                     call.lineno, call.col_offset))
                    return evs
        elif isinstance(func, ast.Name):
            if func.id in ("arm", "disarm") and call.args:
                evs.extend(self._chaos(func.id, call))
        return evs

    def _chaos(self, tail, call):
        if not (self.path.startswith(_CHAOS_PATHS)
                or "/tests/" in self.path):
            return []
        if tail == "arm" and any(k.arg in ("count", "hits")
                                 for k in call.keywords):
            return []             # auto-expiring arm — self-limiting
        key = _key_text(call.args[0])
        op = "acquire" if tail == "arm" else "release"
        return [self._keyed(op, "chaos-failpoint", key, call)]

    def _keyed(self, op, proto, key, call):
        res = f"k:{proto}:{key[:_MAX_KEY]}"
        seen = self.keyed_seen.setdefault(res, {"acquire": 0,
                                                "release": 0})
        seen[op] += 1
        return Event(op, proto, res, call.lineno, call.col_offset)

    # -- escapes -------------------------------------------------------------
    def _escape_events(self, stmt):
        evs = []
        kind = "store"
        bare_ok = False
        if isinstance(stmt, ast.Return):
            kind = "return"
        elif isinstance(stmt, (ast.If, ast.While, ast.Assert)):
            bare_ok = True        # truthiness tests read, not move
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            kind = "with"
        hits = {}
        for expr in header_exprs(stmt):
            if expr is not None:
                self._scan(expr, hits, kind, bare_ok)
        for name, (esc_kind, lineno, col, call_pos) in hits.items():
            evs.append(Event("escape", self.handles[name], f"h:{name}",
                             lineno, col, esc_kind=esc_kind,
                             call_pos=call_pos))
        return evs

    def _scan(self, expr, hits, kind, bare_ok, call_pos=None):
        if isinstance(expr, ast.Name):
            if expr.id in self.handles and \
                    isinstance(expr.ctx, ast.Load) and not bare_ok:
                hits.setdefault(expr.id, (
                    "arg" if call_pos else kind, expr.lineno,
                    expr.col_offset, call_pos))
            return
        if isinstance(expr, ast.Lambda):
            for sub in ast.walk(expr.body):
                if isinstance(sub, ast.Name) and \
                        sub.id in self.handles:
                    hits.setdefault(sub.id, ("closure", sub.lineno,
                                             sub.col_offset, None))
            return
        if isinstance(expr, ast.Call):
            # receiver chains rooted at a handle are reads, not moves
            func = expr.func
            if isinstance(func, ast.Attribute):
                root = _root_name(func)
                if root is None or root not in self.handles:
                    self._scan(func.value, hits, kind, False)
            for j, arg in enumerate(expr.args):
                if isinstance(arg, ast.Name) and \
                        (id(expr), arg.id) in self.consumed:
                    continue      # this occurrence IS the release
                self._scan(arg, hits, kind, False,
                           call_pos=((expr.lineno, expr.col_offset), j))
            for kw in expr.keywords:
                self._scan(kw.value, hits, kind, False,
                           call_pos=((expr.lineno, expr.col_offset),
                                     None))
            return
        if isinstance(expr, ast.Attribute):
            root = _root_name(expr)
            if root in self.handles and not bare_ok and \
                    isinstance(expr.ctx, ast.Load):
                # a field of the handle flowing into a value — treat
                # as escape (conservative: silence over speculation)
                hits.setdefault(root, ("field", expr.lineno,
                                       expr.col_offset, call_pos))
            return
        for child in ast.iter_child_nodes(expr):
            self._scan(child, hits, kind, bare_ok, call_pos=call_pos)


# -- the dataflow -------------------------------------------------------------
def _apply(events, state, res, exc):
    for ev in events:
        if ev.res != res:
            continue
        if ev.op == "acquire":
            if not exc:           # acquire's exception edge = PRE-state
                state = frozenset({("A", ev.lineno)})
        elif ev.op == "release":
            state = frozenset({("R", ev.lineno)})
        elif ev.op == "escape":
            state = frozenset({("E", ev.lineno)})
    return state


def _run_dataflow(cfg, events_by_node, res):
    n = len(cfg.nodes)
    IN = [None] * n
    IN[cfg.entry] = frozenset({("U", 0)})
    work = deque([cfg.entry])
    while work:
        i = work.popleft()
        evs = events_by_node.get(i, ())
        out_n = _apply(evs, IN[i], res, exc=False)
        out_e = _apply(evs, IN[i], res, exc=True)
        for j, kind in cfg.nodes[i].succs:
            contrib = out_e if kind == EXCEPTION else out_n
            if IN[j] is None:
                IN[j] = contrib
                work.append(j)
            elif not contrib <= IN[j]:
                IN[j] = IN[j] | contrib
                work.append(j)
    return IN


def _blame_line(cfg, IN, events_by_node, res, acq_line):
    """The source line whose exception edge first carries the leak out
    (best-effort provenance for the finding message)."""
    releasing = set()
    for idx, evs in events_by_node.items():
        if any(e.res == res and e.op in ("release", "escape")
               for e in evs):
            releasing.add(idx)
    keep = {cfg.raise_exit}
    preds = cfg.preds()
    stack = [cfg.raise_exit]
    while stack:
        i = stack.pop()
        for p, _kind in preds[i]:
            if p in keep or p in releasing:
                continue
            keep.add(p)
            stack.append(p)
    best = None
    for node in cfg.nodes:
        if IN[node.idx] is None:
            continue
        exc_succs = [j for j, k in node.succs if k == EXCEPTION]
        if not exc_succs or not any(j in keep for j in exc_succs):
            continue
        out_e = _apply(events_by_node.get(node.idx, ()), IN[node.idx],
                       res, exc=True)
        if ("A", acq_line) in out_e and node.lineno:
            if best is None or node.lineno < best:
                best = node.lineno
    return best if best is not None else acq_line


# -- per-function analysis ----------------------------------------------------
class _Entry:
    """One report entry (leak / double-release / pairing)."""

    __slots__ = ("fs", "proto", "label", "lineno", "col", "detail")

    def __init__(self, fs, proto, label, lineno, col, detail):
        self.fs = fs
        self.proto = proto
        self.label = label        # handle name or keyed label
        self.lineno = lineno
        self.col = col
        self.detail = detail      # per-kind payload dict


class LifecycleReport:
    __slots__ = ("leaks", "double_releases", "pairs", "escapes",
                 "skipped_capped", "analyzed_functions")

    def __init__(self):
        self.leaks = []
        self.double_releases = []
        self.pairs = []           # acquire/release held-set pairings
        self.escapes = []         # (fs, res, esc classification)
        self.skipped_capped = []
        self.analyzed_functions = 0


def _tracked_resources(ex):
    """{res: proto} for resources the dataflow should run on."""
    tracked = {}
    by_res = {}
    for evs in ex.events.values():
        for ev in evs:
            by_res.setdefault(ev.res, []).append(ev)
    for res, evs in by_res.items():
        proto = evs[0].proto
        has_acq = any(e.op == "acquire" for e in evs)
        has_rel = any(e.op == "release" for e in evs)
        has_esc = any(e.op == "escape" for e in evs)
        if not has_acq:
            continue
        if res.startswith("k:"):
            if has_rel:           # keyed: both halves, identical key
                tracked[res] = proto
        elif has_rel or (proto in TRACK_ON_ESCAPE and has_esc):
            tracked[res] = proto
    return tracked


def _res_label(res):
    if res.startswith("h:"):
        return res[2:]
    return res[2:]                # "proto:key"


def _analyze_function(program, fs, report, releasing):
    func = fs.ast_node
    ex = _Extractor(fs.path).run(func)
    if not ex.events:
        return
    tracked = _tracked_resources(ex)
    _classify_escapes(program, fs, ex, report, releasing)
    if not tracked:
        return
    cfg = build_cfg(func)
    if cfg.capped:
        report.skipped_capped.append(fs.id)
        return
    report.analyzed_functions += 1
    events_by_node = {}
    for node in cfg.nodes:
        if node.stmt is not None and id(node.stmt) in ex.events:
            events_by_node[node.idx] = ex.events[id(node.stmt)]
    held_at = {(c.lineno, c.col): c.held for c in fs.calls}

    for res, proto in sorted(tracked.items()):
        IN = _run_dataflow(cfg, events_by_node, res)
        label = _res_label(res)
        all_evs = [e for evs in ex.events.values() for e in evs
                   if e.res == res]
        # leak-on-raise: acquired state reaches the exceptional exit
        raise_in = IN[cfg.raise_exit]
        if raise_in:
            for tag, line in sorted(raise_in):
                if tag != "A":
                    continue
                blame = _blame_line(cfg, IN, events_by_node, res, line)
                report.leaks.append(_Entry(
                    fs, proto, label, line, 0,
                    {"blame_line": blame}))
        # double release: must-analysis on every release node
        seen_dr = set()
        for node in cfg.nodes:
            evs = events_by_node.get(node.idx, ())
            rel = [e for e in evs if e.res == res and e.op == "release"]
            if not rel or IN[node.idx] is None or not IN[node.idx]:
                continue
            if proto not in DOUBLE_RELEASE_PROTOS:
                continue
            if all(tag == "R" for tag, _ln in IN[node.idx]):
                ev = rel[0]
                if (res, ev.lineno) in seen_dr:
                    continue
                seen_dr.add((res, ev.lineno))
                prior = min(ln for _t, ln in IN[node.idx])
                report.double_releases.append(_Entry(
                    fs, proto, label, ev.lineno, ev.col,
                    {"prior_line": prior}))
        # acquire/release held-set pairing (wrong-lock raw material)
        acqs = [e for e in all_evs if e.op == "acquire"]
        rels = [e for e in all_evs if e.op == "release"]
        if acqs and rels and proto not in WRONG_LOCK_EXEMPT:
            a = acqs[0]
            a_held = held_at.get((a.lineno, a.col))
            for r in rels:
                r_held = held_at.get((r.lineno, r.col))
                if a_held is None or r_held is None:
                    continue
                report.pairs.append(_Entry(
                    fs, proto, label, r.lineno, r.col,
                    {"acq_line": a.lineno, "acq_held": a_held,
                     "rel_held": r_held}))


def _classify_escapes(program, fs, ex, report, releasing):
    """Label each escape (transfer / releasing-callee / open-world) —
    introspection only, never findings."""
    site = {(c.lineno, c.col): c for c in fs.calls}
    for evs in ex.events.values():
        for ev in evs:
            if ev.op != "escape":
                continue
            label = ev.esc_kind or "escape"
            if ev.esc_kind == "arg" and ev.call_pos is not None:
                (line, col), j = ev.call_pos
                call = site.get((line, col))
                callee = call.callee if call is not None else None
                if callee is None:
                    label = "arg:open-world"
                else:
                    label = "arg:callee"
                    if j is not None and _releases_param_at(
                            program, releasing, callee, call.kind, j):
                        label = "arg:transfer-release"
            report.escapes.append((fs.id, ev.res, label, ev.lineno))


# -- releasing-callee summaries ----------------------------------------------
def _function_params(fs):
    node = getattr(fs, "ast_node", None)
    if node is None:
        return None
    args = node.args
    return [a.arg for a in list(getattr(args, "posonlyargs", []))
            + list(args.args)]


def _releasing_params(program):
    """fid -> set of parameter names the function provably releases
    (directly, or by forwarding to a releasing callee — depth-limited
    fixpoint over the resolved call graph)."""
    released = {}
    forwards = []
    for fs in program.functions.values():
        params = _function_params(fs)
        if not params:
            continue
        pset = set(params)
        for stmt in iter_own_statements(fs.ast_node):
            for call in _calls_in(header_exprs(stmt)):
                func = call.func
                if isinstance(func, ast.Attribute):
                    recv = _dotted(func.value)
                    low = recv.lower() if recv else ""
                    if func.attr in ("release", "free") and \
                            "pool" in low and len(call.args) == 1:
                        root = _root_name(call.args[0])
                        if root in pset:
                            released.setdefault(fs.id, set()).add(root)
                    elif func.attr in ("close", "finish", "release",
                                       "join") and \
                            isinstance(func.value, ast.Name) and \
                            func.value.id in pset:
                        released.setdefault(fs.id, set()).add(
                            func.value.id)
                for j, arg in enumerate(call.args):
                    if isinstance(arg, ast.Name) and arg.id in pset:
                        forwards.append((fs.id, arg.id,
                                         (call.lineno,
                                          call.col_offset), j))
    site = {}
    for fs in program.functions.values():
        for c in fs.calls:
            if c.callee:
                site[(fs.id, c.lineno, c.col)] = (c.callee, c.kind)
    for _round in range(_FIXPOINT_ROUNDS):
        changed = False
        for fid, param, key, j in forwards:
            ent = site.get((fid,) + key)
            if ent is None:
                continue
            callee_id, kind = ent
            if _releases_param_at(program, released, callee_id, kind,
                                  j):
                cur = released.setdefault(fid, set())
                if param not in cur:
                    cur.add(param)
                    changed = True
        if not changed:
            break
    return released


def _releases_param_at(program, released, callee_id, call_kind, j):
    rel = released.get(callee_id)
    if not rel:
        return False
    callee = program.functions.get(callee_id)
    params = _function_params(callee) if callee is not None else None
    if not params:
        return False
    idx = j + (1 if params[0] == "self" and call_kind != "name" else 0)
    return idx < len(params) and params[idx] in rel


# -- the memoized program-level report ---------------------------------------
def lifecycle_report(program):
    """Compute (once per Program) the lifecycle findings raw material
    shared by the three graph rules."""
    cached = program.__dict__.get("_lifecycle_report")
    if cached is not None:
        return cached
    report = LifecycleReport()
    releasing = _releasing_params(program)
    for fs in sorted(program.functions.values(), key=lambda f: f.id):
        if getattr(fs, "ast_node", None) is None:
            continue
        _analyze_function(program, fs, report, releasing)
    program.__dict__["_lifecycle_report"] = report
    return report
