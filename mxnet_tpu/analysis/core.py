"""graftlint core: rule registry, single-walk AST driver, suppressions,
baseline mechanics, and report rendering.

Design:

* **Single walk.**  Each file is parsed once and traversed once; every
  registered rule receives ``visit``/``depart`` callbacks on every node,
  sharing one :class:`Context` (class/function stacks, lock depth, loop
  depth).  Rules keep their own accumulators and usually report from
  ``depart`` of a class/function once enough context has been seen.
* **Suppressions.**  ``# graftlint: disable=<rule>[,<rule>...] -- reason``
  on the flagged line (or the line directly above) silences those rules
  for that line.  ``disable=all`` silences everything.  The reason text
  after ``--`` is required by convention (reviewed, not enforced).
* **Baseline.**  A committed JSON file maps finding *fingerprints*
  (stable across line-number drift: rule + path + symbol) to occurrence
  counts.  ``--fail-on-new`` fails only on findings whose fingerprint
  count exceeds the baseline, so the debt ratchet only tightens.
* **Two phases.**  Lexical rules report during the walk; *graph rules*
  (:class:`GraphRule`) run afterwards over the whole-program
  :class:`~summary.Program` built by the summary collector that rides
  the same walk (one parse, one traversal per file either way).  Graph
  findings land at a (path, line) like any other and the same
  suppression / baseline mechanics apply.
"""
from __future__ import annotations

import ast
import json
import os
import re
import time

SEVERITIES = ("error", "warning", "info")

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\-]+)")

# substrings identifying an attribute/name as a synchronization object;
# `with <lockish>:` bumps Context.lock_depth
_LOCKISH_TOKENS = ("lock", "cond", "mutex")


class Finding:
    """One rule violation at one source location."""

    __slots__ = ("rule", "severity", "path", "line", "col", "message",
                 "symbol")

    def __init__(self, rule, severity, path, line, col, message, symbol):
        self.rule = rule
        self.severity = severity
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        # symbol is the rule-chosen stable identity (attribute, env-var
        # name, scope) — the part of the fingerprint that survives line
        # drift, so baselines do not churn on unrelated edits
        self.symbol = symbol

    @property
    def fingerprint(self):
        return f"{self.rule}|{self.path}|{self.symbol}"

    def as_dict(self):
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message, "symbol": self.symbol,
                "fingerprint": self.fingerprint}

    def __repr__(self):
        return (f"Finding({self.rule}, {self.path}:{self.line}, "
                f"{self.symbol!r})")


class Context:
    """Shared traversal state handed to every rule callback."""

    def __init__(self, path):
        self.path = path.replace(os.sep, "/")
        self.findings = []
        self.class_stack = []   # ast.ClassDef nodes, outermost first
        self.func_stack = []    # ast.FunctionDef/AsyncFunctionDef/Lambda
        self.lock_depth = 0     # inside `with self._lock:` style blocks
        self.loop_depth = 0     # inside for/while bodies, comprehensions

    # -- rule-facing helpers -------------------------------------------------
    @property
    def current_class(self):
        return self.class_stack[-1] if self.class_stack else None

    @property
    def current_func(self):
        return self.func_stack[-1] if self.func_stack else None

    def func_name(self):
        f = self.current_func
        if f is None:
            return "<module>"
        return getattr(f, "name", "<lambda>")

    def in_lock(self):
        return self.lock_depth > 0

    def in_loop(self):
        return self.loop_depth > 0

    def report(self, rule, node, message, symbol=None):
        scope = ".".join([c.name for c in self.class_stack]
                         + [self.func_name()]
                         if self.func_stack or self.class_stack else [])
        sym = symbol if symbol is not None else scope or "<module>"
        self.findings.append(Finding(
            rule.id, rule.severity, self.path,
            getattr(node, "lineno", 0), getattr(node, "col_offset", 0),
            message, sym))


class Rule:
    """Base class: subclass, set ``id``/``severity``/``doc``, implement
    the callbacks you need, and decorate with ``@register_rule``."""

    id = ""
    severity = "warning"
    doc = ""

    def begin_file(self, ctx):
        """Reset per-file state."""

    def visit(self, node, ctx):
        """Called for every node, before its children."""

    def depart(self, node, ctx):
        """Called for every node, after its children."""

    def end_file(self, ctx):
        """Flush file-level findings."""


_RULES = {}
_GRAPH_RULES = {}


def register_rule(cls):
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    _RULES[cls.id] = cls
    return cls


def all_rules():
    """{rule_id: rule class} for every registered rule."""
    return dict(_RULES)


def make_rules(select=None, disable=()):
    """Fresh rule instances (rules are stateful within a run)."""
    ids = list(_RULES)
    if select:
        unknown = set(select) - set(ids)
        if unknown:
            raise ValueError(f"unknown rules: {sorted(unknown)}")
        ids = [i for i in ids if i in set(select)]
    ids = [i for i in ids if i not in set(disable)]
    return [_RULES[i]() for i in ids]


class GraphRule:
    """Phase-2 rule: runs once over the whole-program summary graph.

    Subclass, set ``id``/``severity``/``doc``, implement
    ``run(program)`` returning a list of :class:`Finding`, and decorate
    with ``@register_graph_rule``.  ``program`` is a
    :class:`summary.Program` with resolved call edges and the
    collective/lock closures already computed."""

    id = ""
    severity = "warning"
    doc = ""

    def run(self, program):
        return []

    def finding(self, path, line, col, message, symbol):
        return Finding(self.id, self.severity, path, line, col,
                       message, symbol)


def register_graph_rule(cls):
    if not cls.id:
        raise ValueError(f"graph rule {cls.__name__} has no id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    _GRAPH_RULES[cls.id] = cls
    return cls


def all_graph_rules():
    """{rule_id: rule class} for every registered graph rule."""
    return dict(_GRAPH_RULES)


def make_graph_rules(select=None, disable=()):
    ids = list(_GRAPH_RULES)
    if select:
        ids = [i for i in ids if i in set(select)]
    ids = [i for i in ids if i not in set(disable)]
    return [_GRAPH_RULES[i]() for i in ids]


# -- lock detection shared by core and rules ---------------------------------
def is_lockish_name(name):
    low = name.lower()
    return (any(t in low for t in _LOCKISH_TOKENS)
            or low.endswith("_cv") or low == "cv")


def _is_lockish_expr(expr):
    if isinstance(expr, ast.Attribute):
        return is_lockish_name(expr.attr)
    if isinstance(expr, ast.Name):
        return is_lockish_name(expr.id)
    return False


# -- the single walk ---------------------------------------------------------
_LOOP_NODES = (ast.While,)
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk(node, ctx, rules):
    is_class = isinstance(node, ast.ClassDef)
    is_func = isinstance(node, _FUNC_NODES)
    is_loop = isinstance(node, _LOOP_NODES)
    is_for = isinstance(node, (ast.For, ast.AsyncFor))
    is_comp = isinstance(node, _COMP_NODES)
    lockish = (isinstance(node, (ast.With, ast.AsyncWith)) and
               any(_is_lockish_expr(it.context_expr) for it in node.items))

    if is_class:
        ctx.class_stack.append(node)
    if is_func:
        ctx.func_stack.append(node)
    if lockish:
        ctx.lock_depth += 1

    for r in rules:
        r.visit(node, ctx)

    if is_for:
        # target/iter evaluate once, outside the loop body
        _walk(node.target, ctx, rules)
        _walk(node.iter, ctx, rules)
        ctx.loop_depth += 1
        for child in node.body + node.orelse:
            _walk(child, ctx, rules)
        ctx.loop_depth -= 1
    elif is_comp:
        # the first generator's source iterable evaluates once; the
        # element expression and remaining clauses run per item
        gen0 = node.generators[0]
        _walk(gen0.iter, ctx, rules)
        ctx.loop_depth += 1
        _walk(gen0.target, ctx, rules)
        for cond in gen0.ifs:
            _walk(cond, ctx, rules)
        for gen in node.generators[1:]:
            _walk(gen.target, ctx, rules)
            _walk(gen.iter, ctx, rules)
            for cond in gen.ifs:
                _walk(cond, ctx, rules)
        if isinstance(node, ast.DictComp):
            _walk(node.key, ctx, rules)
            _walk(node.value, ctx, rules)
        else:
            _walk(node.elt, ctx, rules)
        ctx.loop_depth -= 1
    elif is_loop:
        ctx.loop_depth += 1
        for child in ast.iter_child_nodes(node):
            _walk(child, ctx, rules)
        ctx.loop_depth -= 1
    else:
        for child in ast.iter_child_nodes(node):
            _walk(child, ctx, rules)

    for r in rules:
        r.depart(node, ctx)

    if lockish:
        ctx.lock_depth -= 1
    if is_func:
        ctx.func_stack.pop()
    if is_class:
        ctx.class_stack.pop()


# -- suppressions ------------------------------------------------------------
def _suppressions(source):
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _is_suppressed(finding, supp):
    for ln in (finding.line, finding.line - 1):
        rules = supp.get(ln)
        if rules and ("all" in rules or finding.rule in rules):
            return True
    return False


# -- timings -----------------------------------------------------------------
class _TimedRule:
    """Per-rule wall-time proxy: forwards every callback, accumulating
    ``perf_counter`` deltas.  Only constructed under ``--timings`` —
    the clock reads roughly double per-node dispatch cost."""

    __slots__ = ("_rule", "id", "severity", "elapsed")

    def __init__(self, rule):
        self._rule = rule
        self.id = rule.id
        self.severity = rule.severity
        self.elapsed = 0.0

    def begin_file(self, ctx):
        t0 = time.perf_counter()
        self._rule.begin_file(ctx)
        self.elapsed += time.perf_counter() - t0

    def visit(self, node, ctx):
        t0 = time.perf_counter()
        self._rule.visit(node, ctx)
        self.elapsed += time.perf_counter() - t0

    def depart(self, node, ctx):
        t0 = time.perf_counter()
        self._rule.depart(node, ctx)
        self.elapsed += time.perf_counter() - t0

    def end_file(self, ctx):
        t0 = time.perf_counter()
        self._rule.end_file(ctx)
        self.elapsed += time.perf_counter() - t0


class ProjectResult:
    """What ``analyze_project`` hands back: the merged findings, parse
    errors, the whole-program summary graph, and (under ``--timings``)
    the per-rule wall-time table."""

    __slots__ = ("findings", "errors", "program", "timings")

    def __init__(self, findings, errors, program, timings):
        self.findings = findings
        self.errors = errors
        self.program = program
        self.timings = timings


# -- entry points ------------------------------------------------------------
def analyze_source(source, path="<string>", rules=None):
    """Lint one source string with the LEXICAL rules; returns the
    (unsuppressed) findings.  Whole-program rules need
    ``analyze_project``/``analyze_sources``."""
    if rules is None:
        rules = make_rules()
    tree = ast.parse(source, filename=path)
    ctx = Context(path)
    for r in rules:
        r.begin_file(ctx)
    _walk(tree, ctx, rules)
    for r in rules:
        r.end_file(ctx)
    supp = _suppressions(source)
    return [f for f in ctx.findings if not _is_suppressed(f, supp)]


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith(".")
                                     and d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _iter_sources(paths, root):
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, root) if root else path
        try:
            with open(path, encoding="utf-8") as f:
                yield rel, f.read()
        except UnicodeDecodeError as e:
            yield rel, e


def analyze_project(paths, rules=None, graph_rules=None, root=None,
                    timings=False):
    """The two-phase engine over every ``.py`` under ``paths``.

    Phase 1: one parse + one walk per file runs the lexical rules AND
    the summary collector.  Phase 2: the call graph is resolved over
    the collected summaries and each graph rule runs once over it.
    Suppression comments apply to both phases (a graph finding landing
    on a suppressed line is silenced like any other).  Paths in
    findings are made relative to ``root`` (stable fingerprints).
    """
    if rules is None:
        rules = make_rules()
    if graph_rules is None:
        graph_rules = make_graph_rules()
    return _analyze_file_set(_iter_sources(paths, root), rules,
                             graph_rules, timings)


def analyze_sources(sources, rules=None, graph_rules=None):
    """Two-phase analysis over in-memory ``{path: source}`` mappings —
    the fixture-test entry point for whole-program rules."""
    if rules is None:
        rules = make_rules()
    if graph_rules is None:
        graph_rules = make_graph_rules()
    items = sorted(sources.items())
    return _analyze_file_set(iter(items), rules, graph_rules,
                             False).findings


def _analyze_file_set(items, rules, graph_rules, timings):
    from .summary import Program, SummaryCollector

    program = Program()
    collector = SummaryCollector(program)
    walk_rules = list(rules) + [collector]
    timed = None
    parse_s = 0.0
    if timings:
        walk_rules = [_TimedRule(r) for r in walk_rules]
        timed = walk_rules
    findings, errors = [], []
    supp_by_path = {}
    t_total0 = time.perf_counter()
    for rel, source in items:
        if isinstance(source, UnicodeDecodeError):
            errors.append((rel, f"UnicodeDecodeError: {source}"))
            continue
        try:
            t0 = time.perf_counter()
            tree = ast.parse(source, filename=rel)
            parse_s += time.perf_counter() - t0
        except SyntaxError as e:
            errors.append((rel, f"SyntaxError: {e}"))
            continue
        ctx = Context(rel)
        for r in walk_rules:
            r.begin_file(ctx)
        _walk(tree, ctx, walk_rules)
        for r in walk_rules:
            r.end_file(ctx)
        supp = _suppressions(source)
        supp_by_path[ctx.path] = supp
        findings.extend(f for f in ctx.findings
                        if not _is_suppressed(f, supp))

    t0 = time.perf_counter()
    program.finish()
    resolve_s = time.perf_counter() - t0

    graph_times = {}
    for gr in graph_rules:
        t0 = time.perf_counter()
        for f in gr.run(program):
            if not _is_suppressed(f, supp_by_path.get(f.path, {})):
                findings.append(f)
        graph_times[gr.id] = time.perf_counter() - t0

    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    timing_table = None
    if timings:
        timing_table = {"(parse)": parse_s,
                        "(call-graph)": resolve_s}
        for tr in timed:
            name = tr.id if tr.id != SummaryCollector.id else \
                "(summaries)"
            timing_table[name] = tr.elapsed
        timing_table.update(graph_times)
        timing_table["(total)"] = time.perf_counter() - t_total0
    return ProjectResult(findings, errors, program, timing_table)


def analyze_paths(paths, rules=None, root=None):
    """Back-compat wrapper: lexical + graph findings as
    ``(findings, errors)``."""
    res = analyze_project(paths, rules=rules, root=root)
    return res.findings, res.errors


# -- baseline ----------------------------------------------------------------
def fingerprint_counts(findings):
    counts = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    return counts


def load_baseline(path):
    """{fingerprint: count} from a baseline file ({} when absent)."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return {str(k): int(v) for k, v in doc.get("findings", {}).items()}


def write_baseline(path, findings):
    """Commit the current findings as the new baseline (atomic write)."""
    doc = {
        "comment": "graftlint baseline — regenerate with "
                   "`python tools/graftlint.py --write-baseline`; "
                   "--fail-on-new fails only findings not counted here, "
                   "so this file should only ever shrink",
        "findings": dict(sorted(fingerprint_counts(findings).items())),
    }
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def diff_baseline(findings, baseline):
    """Split findings into (new, old) against a baseline count map.

    The first ``baseline[fp]`` occurrences of each fingerprint are old
    debt; anything beyond that is new and should fail the gate.
    """
    seen = {}
    new, old = [], []
    for f in findings:
        idx = seen.get(f.fingerprint, 0)
        seen[f.fingerprint] = idx + 1
        (old if idx < baseline.get(f.fingerprint, 0) else new).append(f)
    return new, old


# -- rendering ---------------------------------------------------------------
def render_text(findings, errors=(), title="graftlint"):
    lines = []
    for f in findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: [{f.severity}] "
                     f"{f.rule}: {f.message}")
    for path, msg in errors:
        lines.append(f"{path}: [error] parse-error: {msg}")
    by_rule = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
    lines.append(f"{title}: {len(findings)} finding(s)"
                 + (f" ({summary})" if summary else ""))
    return "\n".join(lines)


JSON_SCHEMA_VERSION = 2


def render_json(findings, errors=(), call_graph=None, timings=None):
    doc = {
        "schema_version": JSON_SCHEMA_VERSION,
        "findings": [f.as_dict() for f in findings],
        "parse_errors": [{"path": p, "message": m} for p, m in errors],
    }
    if call_graph is not None:
        doc["call_graph"] = dict(call_graph)
    if timings is not None:
        doc["timings"] = {k: round(v, 4) for k, v in timings.items()}
    return json.dumps(doc, indent=1)


def render_timings(timings):
    """Per-rule wall-time table (``--timings``), slowest first."""
    rows = sorted(((v, k) for k, v in timings.items() if k != "(total)"),
                  reverse=True)
    total = timings.get("(total)", 0.0)
    lines = ["graftlint timings (where lint time goes):"]
    for v, k in rows:
        pct = 100.0 * v / total if total else 0.0
        lines.append(f"  {k:<28} {v * 1e3:9.1f} ms  {pct:5.1f}%")
    lines.append(f"  {'(total)':<28} {total * 1e3:9.1f} ms")
    return "\n".join(lines)
