"""graftlint core: rule registry, single-walk AST driver, suppressions,
baseline mechanics, and report rendering.

Design:

* **Single walk.**  Each file is parsed once and traversed once; every
  registered rule receives ``visit``/``depart`` callbacks on every node,
  sharing one :class:`Context` (class/function stacks, lock depth, loop
  depth).  Rules keep their own accumulators and usually report from
  ``depart`` of a class/function once enough context has been seen.
* **Suppressions.**  ``# graftlint: disable=<rule>[,<rule>...] -- reason``
  on the flagged line (or the line directly above) silences those rules
  for that line.  ``disable=all`` silences everything.  The reason text
  after ``--`` is required by convention (reviewed, not enforced).
* **Baseline.**  A committed JSON file maps finding *fingerprints*
  (stable across line-number drift: rule + path + symbol) to occurrence
  counts.  ``--fail-on-new`` fails only on findings whose fingerprint
  count exceeds the baseline, so the debt ratchet only tightens.
"""
from __future__ import annotations

import ast
import json
import os
import re

SEVERITIES = ("error", "warning", "info")

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\-]+)")

# substrings identifying an attribute/name as a synchronization object;
# `with <lockish>:` bumps Context.lock_depth
_LOCKISH_TOKENS = ("lock", "cond", "mutex")


class Finding:
    """One rule violation at one source location."""

    __slots__ = ("rule", "severity", "path", "line", "col", "message",
                 "symbol")

    def __init__(self, rule, severity, path, line, col, message, symbol):
        self.rule = rule
        self.severity = severity
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        # symbol is the rule-chosen stable identity (attribute, env-var
        # name, scope) — the part of the fingerprint that survives line
        # drift, so baselines do not churn on unrelated edits
        self.symbol = symbol

    @property
    def fingerprint(self):
        return f"{self.rule}|{self.path}|{self.symbol}"

    def as_dict(self):
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message, "symbol": self.symbol,
                "fingerprint": self.fingerprint}

    def __repr__(self):
        return (f"Finding({self.rule}, {self.path}:{self.line}, "
                f"{self.symbol!r})")


class Context:
    """Shared traversal state handed to every rule callback."""

    def __init__(self, path):
        self.path = path.replace(os.sep, "/")
        self.findings = []
        self.class_stack = []   # ast.ClassDef nodes, outermost first
        self.func_stack = []    # ast.FunctionDef/AsyncFunctionDef/Lambda
        self.lock_depth = 0     # inside `with self._lock:` style blocks
        self.loop_depth = 0     # inside for/while bodies, comprehensions

    # -- rule-facing helpers -------------------------------------------------
    @property
    def current_class(self):
        return self.class_stack[-1] if self.class_stack else None

    @property
    def current_func(self):
        return self.func_stack[-1] if self.func_stack else None

    def func_name(self):
        f = self.current_func
        if f is None:
            return "<module>"
        return getattr(f, "name", "<lambda>")

    def in_lock(self):
        return self.lock_depth > 0

    def in_loop(self):
        return self.loop_depth > 0

    def report(self, rule, node, message, symbol=None):
        scope = ".".join([c.name for c in self.class_stack]
                         + [self.func_name()]
                         if self.func_stack or self.class_stack else [])
        sym = symbol if symbol is not None else scope or "<module>"
        self.findings.append(Finding(
            rule.id, rule.severity, self.path,
            getattr(node, "lineno", 0), getattr(node, "col_offset", 0),
            message, sym))


class Rule:
    """Base class: subclass, set ``id``/``severity``/``doc``, implement
    the callbacks you need, and decorate with ``@register_rule``."""

    id = ""
    severity = "warning"
    doc = ""

    def begin_file(self, ctx):
        """Reset per-file state."""

    def visit(self, node, ctx):
        """Called for every node, before its children."""

    def depart(self, node, ctx):
        """Called for every node, after its children."""

    def end_file(self, ctx):
        """Flush file-level findings."""


_RULES = {}


def register_rule(cls):
    if not cls.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if cls.severity not in SEVERITIES:
        raise ValueError(f"rule {cls.id}: bad severity {cls.severity!r}")
    _RULES[cls.id] = cls
    return cls


def all_rules():
    """{rule_id: rule class} for every registered rule."""
    return dict(_RULES)


def make_rules(select=None, disable=()):
    """Fresh rule instances (rules are stateful within a run)."""
    ids = list(_RULES)
    if select:
        unknown = set(select) - set(ids)
        if unknown:
            raise ValueError(f"unknown rules: {sorted(unknown)}")
        ids = [i for i in ids if i in set(select)]
    ids = [i for i in ids if i not in set(disable)]
    return [_RULES[i]() for i in ids]


# -- lock detection shared by core and rules ---------------------------------
def is_lockish_name(name):
    low = name.lower()
    return (any(t in low for t in _LOCKISH_TOKENS)
            or low.endswith("_cv") or low == "cv")


def _is_lockish_expr(expr):
    if isinstance(expr, ast.Attribute):
        return is_lockish_name(expr.attr)
    if isinstance(expr, ast.Name):
        return is_lockish_name(expr.id)
    return False


# -- the single walk ---------------------------------------------------------
_LOOP_NODES = (ast.While,)
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _walk(node, ctx, rules):
    is_class = isinstance(node, ast.ClassDef)
    is_func = isinstance(node, _FUNC_NODES)
    is_loop = isinstance(node, _LOOP_NODES)
    is_for = isinstance(node, (ast.For, ast.AsyncFor))
    is_comp = isinstance(node, _COMP_NODES)
    lockish = (isinstance(node, (ast.With, ast.AsyncWith)) and
               any(_is_lockish_expr(it.context_expr) for it in node.items))

    if is_class:
        ctx.class_stack.append(node)
    if is_func:
        ctx.func_stack.append(node)
    if lockish:
        ctx.lock_depth += 1

    for r in rules:
        r.visit(node, ctx)

    if is_for:
        # target/iter evaluate once, outside the loop body
        _walk(node.target, ctx, rules)
        _walk(node.iter, ctx, rules)
        ctx.loop_depth += 1
        for child in node.body + node.orelse:
            _walk(child, ctx, rules)
        ctx.loop_depth -= 1
    elif is_comp:
        # the first generator's source iterable evaluates once; the
        # element expression and remaining clauses run per item
        gen0 = node.generators[0]
        _walk(gen0.iter, ctx, rules)
        ctx.loop_depth += 1
        _walk(gen0.target, ctx, rules)
        for cond in gen0.ifs:
            _walk(cond, ctx, rules)
        for gen in node.generators[1:]:
            _walk(gen.target, ctx, rules)
            _walk(gen.iter, ctx, rules)
            for cond in gen.ifs:
                _walk(cond, ctx, rules)
        if isinstance(node, ast.DictComp):
            _walk(node.key, ctx, rules)
            _walk(node.value, ctx, rules)
        else:
            _walk(node.elt, ctx, rules)
        ctx.loop_depth -= 1
    elif is_loop:
        ctx.loop_depth += 1
        for child in ast.iter_child_nodes(node):
            _walk(child, ctx, rules)
        ctx.loop_depth -= 1
    else:
        for child in ast.iter_child_nodes(node):
            _walk(child, ctx, rules)

    for r in rules:
        r.depart(node, ctx)

    if lockish:
        ctx.lock_depth -= 1
    if is_func:
        ctx.func_stack.pop()
    if is_class:
        ctx.class_stack.pop()


# -- suppressions ------------------------------------------------------------
def _suppressions(source):
    out = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _is_suppressed(finding, supp):
    for ln in (finding.line, finding.line - 1):
        rules = supp.get(ln)
        if rules and ("all" in rules or finding.rule in rules):
            return True
    return False


# -- entry points ------------------------------------------------------------
def analyze_source(source, path="<string>", rules=None):
    """Lint one source string; returns the (unsuppressed) findings."""
    if rules is None:
        rules = make_rules()
    tree = ast.parse(source, filename=path)
    ctx = Context(path)
    for r in rules:
        r.begin_file(ctx)
    _walk(tree, ctx, rules)
    for r in rules:
        r.end_file(ctx)
    supp = _suppressions(source)
    return [f for f in ctx.findings if not _is_suppressed(f, supp)]


def iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith(".")
                                     and d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def analyze_paths(paths, rules=None, root=None):
    """Lint every ``.py`` under ``paths``; paths in findings are made
    relative to ``root`` (for stable fingerprints)."""
    if rules is None:
        rules = make_rules()
    findings = []
    errors = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, root) if root else path
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            findings.extend(analyze_source(source, path=rel, rules=rules))
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append((rel, f"{type(e).__name__}: {e}"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, errors


# -- baseline ----------------------------------------------------------------
def fingerprint_counts(findings):
    counts = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    return counts


def load_baseline(path):
    """{fingerprint: count} from a baseline file ({} when absent)."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return {str(k): int(v) for k, v in doc.get("findings", {}).items()}


def write_baseline(path, findings):
    """Commit the current findings as the new baseline (atomic write)."""
    doc = {
        "comment": "graftlint baseline — regenerate with "
                   "`python tools/graftlint.py --write-baseline`; "
                   "--fail-on-new fails only findings not counted here, "
                   "so this file should only ever shrink",
        "findings": dict(sorted(fingerprint_counts(findings).items())),
    }
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def diff_baseline(findings, baseline):
    """Split findings into (new, old) against a baseline count map.

    The first ``baseline[fp]`` occurrences of each fingerprint are old
    debt; anything beyond that is new and should fail the gate.
    """
    seen = {}
    new, old = [], []
    for f in findings:
        idx = seen.get(f.fingerprint, 0)
        seen[f.fingerprint] = idx + 1
        (old if idx < baseline.get(f.fingerprint, 0) else new).append(f)
    return new, old


# -- rendering ---------------------------------------------------------------
def render_text(findings, errors=(), title="graftlint"):
    lines = []
    for f in findings:
        lines.append(f"{f.path}:{f.line}:{f.col}: [{f.severity}] "
                     f"{f.rule}: {f.message}")
    for path, msg in errors:
        lines.append(f"{path}: [error] parse-error: {msg}")
    by_rule = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
    lines.append(f"{title}: {len(findings)} finding(s)"
                 + (f" ({summary})" if summary else ""))
    return "\n".join(lines)


def render_json(findings, errors=()):
    return json.dumps({
        "findings": [f.as_dict() for f in findings],
        "parse_errors": [{"path": p, "message": m} for p, m in errors],
    }, indent=1)
