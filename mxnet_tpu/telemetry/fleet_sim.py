"""Fleet-scale observability simulator (ISSUE 20 tentpole).

``python -m mxnet_tpu.telemetry.fleet_sim --ranks 1000`` runs N
synthetic fleet reporters — each with its own seeded metric-family
generator (cardinality drawn from the REAL registry's family catalog,
plus scripted anomalies: a rank going silent, a burn-rate breach, a
numerics page) — against ONE real leader: a real
:class:`~mxnet_tpu.kvstore_server.KVServer` (virtual clock injected),
its real :class:`~mxnet_tpu.telemetry.fleet.FleetStore` merge path
(``KVServer.apply_telemetry_push`` — the exact ``telemetry_push`` op
body), the real :func:`~mxnet_tpu.telemetry.fleet.merge_server`
rollup, and a real :class:`~mxnet_tpu.telemetry.alerts.AlertEngine`
judging the fleet through the registered provider.  Everything runs
in-process with virtualized time, so a 1000-rank, 50-push-cycle run
completes in seconds on a laptop.

The report is machine-readable (``--json``) and the simulator IS the
gate (bench.py ``BENCH_FLEET`` and the CI smoke call it):

* ``merge_p99_ms``  — per-push leader merge cost, p99 < 1 ms;
* ``rollup_ms``     — summary rollup at scrape, max < 50 ms;
* ``scrape_kib``    — summary ``/fleet.json`` bytes, < 256 KiB;
* ``alert lag``     — injected breach -> leader-visible firing,
  < 2 push intervals;
* ``sublinearity``  — rank=1000 merge p99 ≤ 3× rank=100 (a reference
  run at rank=100 precedes the main run);
* plus the back-compat pin: at rank ≤ 8 the delta-pushed store renders
  a ``detail="rank"`` view byte-identical to the pre-ISSUE-20 merge
  path fed the same pushes in full (a shadow legacy store).

Allocation behavior is sampled with :mod:`tracemalloc` over a mid-run
window (docs/observability.md "fleet at scale" runbook).
"""
from __future__ import annotations

import argparse
import gc
import json
import pickle
import random
import sys
import time
import tracemalloc


def _percentile(vals, q):
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


class SimClock:
    """Virtual monotonic clock: the KVServer, FleetStore and
    AlertEngine all read it, so peer timeouts, snapshot ages and alert
    ``for``-durations mature at simulated push-interval speed."""

    def __init__(self, start=1000.0):
        self.now = float(start)

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += float(dt)


# -- synthetic ranks ----------------------------------------------------------
# synthetic families layered ON TOP of whatever the real registry
# already exposes in this process — together they give each rank a
# catalog with realistic cardinality (histogram sample families with le
# labels, label-spread gauges, hot counters, cold config gauges)
_SYNTH_FAMILIES = (
    ("mxnet_sim_step_total", "counter", ({},), True),
    ("mxnet_sim_loss", "gauge", ({},), True),
    ("mxnet_sim_collective_bytes_total", "counter",
     tuple({"op": op} for op in ("push", "pull", "allreduce",
                                 "broadcast")), True),
    ("mxnet_sim_step_seconds_bucket", "counter",
     tuple({"le": le} for le in ("0.01", "0.05", "0.1", "0.5", "1.0",
                                 "+Inf")), True),
    ("mxnet_sim_step_seconds_sum", "counter", ({},), True),
    ("mxnet_sim_step_seconds_count", "counter", ({},), True),
    ("mxnet_sim_queue_depth", "gauge",
     tuple({"lane": str(i)} for i in range(8)), True),
    ("mxnet_sim_device_mem_bytes", "gauge",
     tuple({"device": str(i)} for i in range(4)), False),
    ("mxnet_sim_config_info", "gauge", ({},), False),
    ("mxnet_serving_requests_total", "counter", ({},), True),
    ("mxnet_serving_shed_total", "counter", ({},), True),
    ("mxnet_numerics_nonfinite_windows_total", "counter", ({},), False),
)


def _base_catalog():
    """(family, type, label_sets, hot) rows: the process's REAL
    registry catalog (cold — real families barely move between pushes)
    plus the synthetic hot set above."""
    from . import REGISTRY
    rows = []
    for name, fam in sorted(REGISTRY.sample_families().items()):
        labels = tuple(dict(s.get("labels", {}))
                       for s in fam.get("values", [])[:16])
        if labels:
            rows.append((name, fam.get("type", "gauge"), labels, False))
    rows.extend(_SYNTH_FAMILIES)
    return rows


class SimRank:
    """One synthetic fleet reporter: seeded per-family value streams,
    a real :class:`~.registry.SampleDeltaEncoder`, and scripted
    anomaly hooks (silence / burn-rate breach / numerics page) whose
    ``mxnet_alert_state`` one-hot gauges ride the push exactly like a
    real rank's alert engine output."""

    def __init__(self, rank, seed, catalog, clock, delta=True):
        self.rank = int(rank)
        self.rng = random.Random((int(seed) * 1000003) ^ (rank + 1))
        self._clock = clock
        self.catalog = catalog
        self.silent = False
        self.joined = True
        self.alert_states = {}          # rule -> state (one-hot)
        self._fams = {}                 # family -> current family dict
        self._vals = {}                 # (family, idx) -> value
        if delta:
            from .registry import SampleDeltaEncoder
            self.encoder = SampleDeltaEncoder()
        else:
            self.encoder = None
        for name, mtype, label_sets, _hot in catalog:
            for i in range(len(label_sets)):
                self._vals[(name, i)] = (
                    self.rng.uniform(0, 100) if mtype == "gauge"
                    else float(self.rng.randrange(1000)))
            self._rebuild(name)

    def _rebuild(self, name):
        """Fresh family dict (never mutate in place: the delta encoder
        keeps the previous object as its acked baseline)."""
        for fname, mtype, label_sets, _hot in self.catalog:
            if fname != name:
                continue
            self._fams[name] = {
                "type": mtype,
                "values": [{"labels": dict(ls),
                            "value": self._vals[(name, i)]}
                           for i, ls in enumerate(label_sets)]}
            return

    def step(self):
        """Advance one push interval: hot families move every cycle,
        cold families occasionally — a realistic delta footprint."""
        for name, mtype, label_sets, hot in self.catalog:
            if not hot and self.rng.random() > 0.02:
                continue
            for i in range(len(label_sets)):
                key = (name, i)
                if mtype == "counter":
                    self._vals[key] += self.rng.uniform(0, 50)
                else:
                    self._vals[key] += self.rng.uniform(-1, 1)
            self._rebuild(name)

    def breach_burn_rate(self):
        """Scripted SLO breach: sheds ramp hard and this rank's alert
        engine (simulated output) flips shed_burn_rate to firing."""
        for i in range(1):
            self._vals[("mxnet_serving_shed_total", i)] += 5000.0
        self._rebuild("mxnet_serving_shed_total")
        self.alert_states["shed_burn_rate"] = "firing"
        self._rebuild_alerts()

    def page_numerics(self):
        """Scripted numerics page: a non-finite window lands."""
        self._vals[("mxnet_numerics_nonfinite_windows_total", 0)] += 1.0
        self._rebuild("mxnet_numerics_nonfinite_windows_total")
        self.alert_states["nonfinite_window"] = "firing"
        self._rebuild_alerts()

    def _rebuild_alerts(self):
        values = []
        for rule, state in self.alert_states.items():
            for s in ("pending", "firing", "resolved", "inactive"):
                values.append({"labels": {"rule": rule, "state": s},
                               "value": 1 if s == state else 0})
        self._fams["mxnet_alert_state"] = {"type": "gauge",
                                           "values": values}

    def payload(self):
        full = {"time": self._clock(), "families": dict(self._fams)}
        if self.encoder is None:
            return full
        return self.encoder.encode(full)

    def full_families(self):
        return dict(self._fams)


# -- the simulation -----------------------------------------------------------
def _make_leader(ranks, interval_s, clock):
    from ..kvstore_server import KVServer
    return KVServer(port=0, num_workers=int(ranks),
                    peer_timeout_s=float(interval_s) * 2.5, clock=clock)


def _heartbeat(server, rank, step, clock):
    # the heartbeat op body (kvstore_server._handle), sans socket
    with server._lock:
        server._heartbeats[int(rank)] = clock()
        server._progress[int(rank)] = int(step)


def run_sim(ranks=1000, cycles=50, interval_s=5.0, seed=0, delta=True,
            churn=None, alloc_window=5, verbose=False):
    """One simulated fleet run; returns the raw stats dict.

    ``churn``: optional ``{"die": [rank...], "die_at": cycle,
    "join": [rank...], "join_at": cycle}`` — joining ranks stay silent
    (state ``unknown``) until ``join_at``; dying ranks stop pushing and
    heartbeating at ``die_at`` and must age to ``lost``.
    """
    from . import fleet
    from .alerts import AlertEngine, default_rules
    from ..chaos.failpoints import failpoint as _failpoint, \
        ChaosInjectedError

    clock = SimClock()
    server = _make_leader(ranks, interval_s, clock)
    catalog = _base_catalog()
    sims = [SimRank(r, seed, catalog, clock, delta=delta)
            for r in range(int(ranks))]
    # The simulator hosts ALL N ranks' object graphs in one process — a
    # topology no real leader has.  Automatic gen-2 GC passes scan those
    # millions of synthetic fixture objects (~100 ms each at rank=1000)
    # and the pause lands inside whichever leader call happens to be
    # running, polluting the merge/rollup gates with pure simulator
    # overhead.  The per-cycle family churn is acyclic (plain dicts and
    # lists), so refcounting reclaims it; defer cycle collection to
    # teardown and keep the measured window collection-free.
    gc.collect()
    gc.freeze()
    gc_was_enabled = gc.isenabled()
    gc.disable()

    churn = churn or {}
    die_set = set(churn.get("die", ()))
    join_set = set(churn.get("join", ()))
    die_at = int(churn.get("die_at", -1))
    join_at = int(churn.get("join_at", 0))
    for s in sims:
        if s.rank in join_set:
            s.joined = False

    # scripted anomalies (skipped for ranks the churn plan controls)
    silent_rank = next((r for r in (7 % ranks, 5 % ranks)
                        if r not in die_set | join_set), 0)
    breach_rank = next((r for r in (11 % ranks, 3 % ranks)
                        if r not in die_set | join_set
                        and r != silent_rank), 1 % ranks)
    numerics_rank = next((r for r in (13 % ranks, 2 % ranks)
                          if r not in die_set | join_set
                          and r not in (silent_rank, breach_rank)),
                         0)
    silent_cycle = max(2, cycles // 2)
    breach_cycle = max(1, cycles // 3)
    numerics_cycle = max(1, (2 * cycles) // 3)

    old_provider = fleet.provider()
    fleet.set_provider(
        lambda detail=None: fleet.merge_server(server, detail=detail,
                                               _now=clock()))
    engine = AlertEngine(rules=default_rules())

    merge_s = []
    rollup_s = []
    scrape_bytes = 0
    wire = {"full": 0, "delta": 0}
    pushes = {"full": 0, "delta": 0, "resync": 0, "dropped": 0}
    leader_exceptions = []
    breach_visible_cycle = None
    alloc = {"bytes_per_cycle": None, "count_per_cycle": None}
    alloc_started = False
    alloc_t0 = None
    summary = {}

    def _push(sim):
        payload = sim.payload()
        try:
            _failpoint("fleet/push")
        except ChaosInjectedError:
            pushes["dropped"] += 1
            return
        mode = "delta" if "delta" in payload else "full"
        wire[mode] += len(pickle.dumps(
            payload, protocol=pickle.HIGHEST_PROTOCOL))
        t0 = time.perf_counter()
        try:
            resp = server.apply_telemetry_push(sim.rank, payload)
        except Exception as e:  # noqa: BLE001 — a leader exception is itself a gated failure, record it
            leader_exceptions.append(f"{type(e).__name__}: {e}")
            return
        merge_s.append(time.perf_counter() - t0)
        if resp.get("resync") and sim.encoder is not None:
            pushes["resync"] += 1
            sim.encoder.reset()
            payload = sim.payload()
            wire["full"] += len(pickle.dumps(
                payload, protocol=pickle.HIGHEST_PROTOCOL))
            t0 = time.perf_counter()
            try:
                resp = server.apply_telemetry_push(sim.rank, payload)
            except Exception as e:  # noqa: BLE001 — see above
                leader_exceptions.append(f"{type(e).__name__}: {e}")
                return
            merge_s.append(time.perf_counter() - t0)
            pushes["full"] += 1
        else:
            pushes[mode] += 1
        if sim.encoder is not None and resp.get("acked") is not None:
            sim.encoder.ack(resp["acked"])

    try:
        for cycle in range(int(cycles)):
            clock.advance(interval_s)
            if alloc_window and cycle == cycles // 2:
                tracemalloc.start()
                alloc_t0 = tracemalloc.take_snapshot()
                alloc_started = True
            if cycle == silent_cycle:
                sims[silent_rank].silent = True
            if cycle == breach_cycle:
                sims[breach_rank].breach_burn_rate()
            if cycle == numerics_cycle:
                sims[numerics_rank].page_numerics()
            if die_at >= 0 and cycle == die_at:
                for s in sims:
                    if s.rank in die_set:
                        s.silent = True
            if cycle == join_at:
                for s in sims:
                    if s.rank in join_set:
                        s.joined = True
            for sim in sims:
                if sim.silent or not sim.joined:
                    continue
                sim.step()
                _heartbeat(server, sim.rank, cycle, clock)
                _push(sim)
            # leader scrape: the summary rollup + the real AlertEngine
            t0 = time.perf_counter()
            try:
                summary = fleet.merge_server(server, detail="summary",
                                             _now=clock())
            except Exception as e:  # noqa: BLE001 — a rollup exception is a gated failure
                leader_exceptions.append(f"{type(e).__name__}: {e}")
                summary = {}
            rollup_s.append(time.perf_counter() - t0)
            scrape_bytes = len(json.dumps(summary, default=str,
                                          sort_keys=True))
            engine.tick(now=clock())
            if breach_visible_cycle is None:
                for f in (summary.get("alerts") or {}).get("firing", ()):
                    if f.get("rank") == str(breach_rank) and \
                            f.get("rule") == "shed_burn_rate":
                        breach_visible_cycle = cycle
                        break
            if alloc_started and cycle == cycles // 2 + alloc_window - 1:
                diff = tracemalloc.take_snapshot().compare_to(
                    alloc_t0, "filename")
                tracemalloc.stop()
                alloc_started = False
                alloc["bytes_per_cycle"] = int(
                    sum(d.size_diff for d in diff) / alloc_window)
                alloc["count_per_cycle"] = int(
                    sum(d.count_diff for d in diff) / alloc_window)
            if verbose and cycle % 10 == 0:
                print(f"[fleet_sim] cycle {cycle}/{cycles} "
                      f"merge_p99={_percentile(merge_s, 0.99)*1e3:.3f}ms",
                      flush=True)
    finally:
        if alloc_started:
            tracemalloc.stop()
        gc.unfreeze()
        if gc_was_enabled:
            gc.enable()
        gc.collect()
        fleet.set_provider(old_provider)

    states = server._peer_states()
    return {
        "ranks": int(ranks), "cycles": int(cycles),
        "interval_s": float(interval_s), "seed": int(seed),
        "delta": bool(delta),
        "merge": {
            "pushes": len(merge_s),
            "p50_ms": _percentile(merge_s, 0.5) * 1e3,
            "p99_ms": _percentile(merge_s, 0.99) * 1e3,
            "max_ms": (max(merge_s) * 1e3) if merge_s else 0.0,
            "full": pushes["full"], "delta": pushes["delta"],
            "resync": pushes["resync"], "dropped": pushes["dropped"]},
        "push_bytes": {
            "full_total": wire["full"], "delta_total": wire["delta"],
            "delta_mean": (wire["delta"] / max(1, pushes["delta"])),
            "full_mean": (wire["full"] / max(1, pushes["full"]))},
        "rollup": {
            "p50_ms": _percentile(rollup_s, 0.5) * 1e3,
            "max_ms": (max(rollup_s) * 1e3) if rollup_s else 0.0},
        "scrape": {"summary_bytes": scrape_bytes,
                   "summary_kib": scrape_bytes / 1024.0},
        "alloc": alloc,
        "alerts": {
            "breach_rank": breach_rank,
            "breach_cycle": breach_cycle,
            "visible_cycle": breach_visible_cycle,
            "lag_intervals": (None if breach_visible_cycle is None
                              else breach_visible_cycle - breach_cycle),
            "leader_firing": sorted(
                a["rule"] for a in
                (summary.get("alerts") or {}).get("firing", ())),
            "silent_rank": silent_rank,
            "silent_rank_state": states.get(silent_rank, {}).get(
                "state"),
            "numerics_rank": numerics_rank},
        "leader_exceptions": leader_exceptions,
        "final_summary": {
            "peers": summary.get("peers"),
            "anomalous": sorted((summary.get("anomalous") or {})),
            "push_stats": summary.get("push_stats")},
    }


# -- back-compat pin ----------------------------------------------------------
def run_backcompat(ranks=8, cycles=6, interval_s=5.0, seed=0):
    """Delta-pushed store vs a shadow pre-ISSUE-20 store fed the SAME
    pushes in full, rendered through the same merge algorithm — the
    detail ``/fleet.json`` must be byte-identical at rank ≤ 8.
    Includes a generation bump mid-run (resync + history) and a silent
    rank (lost/stale tagging on both sides)."""
    from . import fleet

    clock = SimClock()
    server = _make_leader(ranks, interval_s, clock)
    catalog = _base_catalog()
    sims = [SimRank(r, seed, catalog, clock, delta=True)
            for r in range(int(ranks))]
    shadow = {}   # the legacy {gen: {rank: {"payload", "mono"}}} store
    silent_rank = ranks - 1
    resyncs = 0
    for cycle in range(int(cycles)):
        clock.advance(interval_s)
        if cycle == cycles // 2:
            server.reset_world(ranks, generation=1)
        if cycle == cycles - 2:
            sims[silent_rank].silent = True
        if cycle == 1:
            sims[0].breach_burn_rate()   # exercise the alert rollup
        with server._lock:
            gen = server._generation
        for sim in sims:
            if sim.silent:
                continue
            sim.step()
            _heartbeat(server, sim.rank, cycle, clock)
            payload = sim.payload()
            resp = server.apply_telemetry_push(sim.rank, payload)
            if resp.get("resync"):
                resyncs += 1
                sim.encoder.reset()
                resp = server.apply_telemetry_push(sim.rank,
                                                   sim.payload())
            if resp.get("acked") is not None:
                sim.encoder.ack(resp["acked"])
            shadow.setdefault(gen, {})[sim.rank] = {
                "payload": {"time": clock(),
                            "families": sim.full_families()},
                "mono": clock()}
    now_wall = clock()
    new_view = fleet.merge_server(server, detail="rank", _now=now_wall)
    with server._lock:
        gen = server._generation
        world = server.num_workers
    old_view = fleet._merge_view(
        server._peer_states(), gen, world, shadow,
        server._peer_timeout(), clock(), now_wall)
    new_json = json.dumps(new_view, default=str, sort_keys=True)
    old_json = json.dumps(old_view, default=str, sort_keys=True)
    return {"ranks": int(ranks), "cycles": int(cycles),
            "resyncs": resyncs,
            "identical": new_json == old_json,
            "new_bytes": len(new_json), "old_bytes": len(old_json)}


# -- gates + CLI --------------------------------------------------------------
GATE_MERGE_P99_MS = 1.0
GATE_ROLLUP_MS = 50.0
GATE_SCRAPE_KIB = 256.0
GATE_ALERT_LAG = 2
GATE_SUBLINEAR_FACTOR = 3.0


def evaluate(result, reference=None, backcompat=None):
    """The five ISSUE 20 gates (+ the back-compat pin) over a run."""
    lag = result["alerts"]["lag_intervals"]
    gates = {
        "merge_p99_ms": {
            "value": result["merge"]["p99_ms"],
            "limit": GATE_MERGE_P99_MS,
            "ok": result["merge"]["p99_ms"] < GATE_MERGE_P99_MS},
        "rollup_ms": {
            "value": result["rollup"]["max_ms"],
            "limit": GATE_ROLLUP_MS,
            "ok": result["rollup"]["max_ms"] < GATE_ROLLUP_MS},
        "scrape_kib": {
            "value": result["scrape"]["summary_kib"],
            "limit": GATE_SCRAPE_KIB,
            "ok": result["scrape"]["summary_kib"] < GATE_SCRAPE_KIB},
        "alert_lag_intervals": {
            "value": lag, "limit": GATE_ALERT_LAG,
            "ok": lag is not None and lag < GATE_ALERT_LAG},
        "leader_exceptions": {
            "value": len(result["leader_exceptions"]), "limit": 0,
            "ok": not result["leader_exceptions"]},
    }
    if reference is not None:
        ref_p99 = max(1e-6, reference["merge"]["p99_ms"])
        ratio = result["merge"]["p99_ms"] / ref_p99
        gates["sublinear_vs_ref"] = {
            "value": ratio, "limit": GATE_SUBLINEAR_FACTOR,
            "ref_ranks": reference["ranks"],
            "ref_p99_ms": reference["merge"]["p99_ms"],
            "ok": ratio <= GATE_SUBLINEAR_FACTOR}
    if backcompat is not None:
        gates["backcompat_identical"] = {
            "value": backcompat["identical"], "limit": True,
            "ok": bool(backcompat["identical"])}
    return gates


def main(argv=None):
    from ..config import get as _cfg
    ap = argparse.ArgumentParser(
        description="in-process fleet-scale observability simulator "
                    "(ISSUE 20; docs/observability.md 'fleet at scale')")
    ap.add_argument("--ranks", type=int,
                    default=int(_cfg("MXNET_FLEET_SIM_RANKS")))
    ap.add_argument("--cycles", type=int,
                    default=int(_cfg("MXNET_FLEET_SIM_CYCLES")))
    ap.add_argument("--interval", type=float, default=5.0,
                    help="virtual push interval seconds")
    ap.add_argument("--seed", type=int,
                    default=int(_cfg("MXNET_FLEET_SIM_SEED")))
    ap.add_argument("--no-delta", action="store_true",
                    help="force full-snapshot pushes (A/B the plane)")
    ap.add_argument("--reference-ranks", type=int, default=100,
                    help="sublinearity reference run size (0 skips)")
    ap.add_argument("--json", action="store_true",
                    help="print ONLY the machine-readable report")
    args = ap.parse_args(argv)

    delta = not args.no_delta
    t_start = time.perf_counter()
    backcompat = run_backcompat(ranks=min(8, max(2, args.ranks)),
                                seed=args.seed)
    reference = None
    if args.reference_ranks and args.ranks > args.reference_ranks:
        reference = run_sim(ranks=args.reference_ranks,
                            cycles=args.cycles,
                            interval_s=args.interval, seed=args.seed,
                            delta=delta)
    result = run_sim(ranks=args.ranks, cycles=args.cycles,
                     interval_s=args.interval, seed=args.seed,
                     delta=delta, verbose=not args.json)
    gates = evaluate(result, reference=reference, backcompat=backcompat)
    ok = all(g["ok"] for g in gates.values())
    report = {"result": result, "reference": reference,
              "backcompat": backcompat, "gates": gates, "ok": ok,
              "wall_s": time.perf_counter() - t_start}
    if args.json:
        print(json.dumps(report, default=str, sort_keys=True))
    else:
        m, r, s = result["merge"], result["rollup"], result["scrape"]
        print(f"[fleet_sim] ranks={args.ranks} cycles={args.cycles} "
              f"delta={delta} wall={report['wall_s']:.1f}s")
        print(f"[fleet_sim] merge: pushes={m['pushes']} "
              f"p50={m['p50_ms']:.3f}ms p99={m['p99_ms']:.3f}ms "
              f"full={m['full']} delta={m['delta']} "
              f"resync={m['resync']}")
        print(f"[fleet_sim] rollup: p50={r['p50_ms']:.2f}ms "
              f"max={r['max_ms']:.2f}ms  scrape={s['summary_kib']:.1f}"
              f"KiB  alloc/cycle={result['alloc']['bytes_per_cycle']}B")
        print(f"[fleet_sim] push bytes: full_mean="
              f"{result['push_bytes']['full_mean']:.0f} delta_mean="
              f"{result['push_bytes']['delta_mean']:.0f}")
        print(f"[fleet_sim] alerts: lag="
              f"{result['alerts']['lag_intervals']} intervals "
              f"silent rank {result['alerts']['silent_rank']} -> "
              f"{result['alerts']['silent_rank_state']}")
        for name, g in gates.items():
            print(f"[fleet_sim] gate {name}: value={g['value']} "
                  f"limit={g['limit']} -> "
                  f"{'OK' if g['ok'] else 'FAIL'}")
        print(f"FLEET SIM {'OK' if ok else 'FAIL'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
