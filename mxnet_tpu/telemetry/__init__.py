"""mxnet_tpu.telemetry — unified observability layer (ISSUE 5 tentpole).

One import gives four subsystems one set of eyes:

* **spans** (:func:`span`) — nestable, thread-safe timed regions that
  merge into the profiler's chrome-trace stream, jax xplane traces, and
  the ``mxnet_span_seconds`` histogram; ~zero-cost while disabled.
* **registry** (:data:`REGISTRY`) — process-wide counters / gauges /
  histograms plus pull-collectors that absorb ``serving.stats()``,
  ``CheckpointManager.stats()``, profiler dispatch lanes, kvstore wire
  bytes and io staging waits behind ONE :func:`snapshot` and a
  Prometheus :func:`prometheus_dump` / HTTP endpoint
  (``MXNET_TELEMETRY_PORT``).
* **step breakdown** (:mod:`steps`) — ``Module.fit`` attributes each
  train step's wall time to lanes (``data_wait`` / ``h2d_stage`` /
  ``step_dispatch`` / ``device_block`` / ``metric_flush`` /
  ``ckpt_block``), surfaced by ``callback.StepTimeline``.
* **watchdog** (:mod:`watchdog`) — ``MXNET_WATCHDOG_S``: all-thread
  stack + snapshot dumps when the train loop or a serving batcher stops
  making progress.

Enable spans + step lanes with ``MXNET_TELEMETRY=1`` or
:func:`enable`; the registry and collectors are always live (they cost
nothing until read).  See docs/observability.md for the metric catalog,
span naming convention, and the watchdog runbook.
"""
from __future__ import annotations

import sys
import weakref

from . import registry as _registry_mod
from . import spans as _spans
from . import steps as _steps
from . import alerts
from . import fleet
from . import flight
from . import numerics
from . import resources
from . import trace
from . import watchdog
from .exporter import exporter_port, start_exporter, stop_exporter
from .registry import MetricsRegistry, exponential_buckets
from .spans import current_span, disable, enable, enabled, span, span_stack
from .steps import (LANES, current_step_timer, reset_step_stats,
                    step_breakdown, step_timer)

heartbeat = watchdog.beat

#: the process-wide registry every subsystem reports into
REGISTRY = MetricsRegistry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
register_collector = REGISTRY.register_collector

# -- built-in instruments ----------------------------------------------------
_spans._span_hist = REGISTRY.histogram(
    "mxnet_span_seconds", "telemetry.span durations by span name")
_steps._lane_hist = REGISTRY.histogram(
    "mxnet_train_step_lane_seconds",
    "per-train-step time attributed to each breakdown lane")
_steps._step_hist = REGISTRY.histogram(
    "mxnet_train_step_seconds", "train step wall time (fit loop)")
trace._stage_hist = REGISTRY.histogram(
    "mxnet_trace_stage_seconds",
    "per-trace stage durations (end-to-end request/window tracing), "
    "by trace kind and stage name")
trace._e2e_hist = REGISTRY.histogram(
    "mxnet_trace_e2e_seconds",
    "end-to-end latency of finished traces, by trace kind")

_KV_BYTES = REGISTRY.counter(
    "mxnet_kvstore_bytes_total",
    "payload bytes moved through kvstore push/pull, by op")
_KV_OPS = REGISTRY.counter(
    "mxnet_kvstore_ops_total", "kvstore push/pull calls, by op")
_IO_STAGE = REGISTRY.histogram(
    "mxnet_io_stage_seconds",
    "host time spent staging a DataBatch host->device (io.stage_batch)")
_IO_STAGE_BYTES = REGISTRY.counter(
    "mxnet_io_stage_bytes_total", "bytes staged host->device by io")
_DATA_WAIT = REGISTRY.histogram(
    "mxnet_data_wait_seconds",
    "train-thread time blocked waiting on the streaming data plane "
    "(io_pipeline assembler/window feed); the data_wait step lane's "
    "registry twin — rising _sum rate means training is data-bound "
    "(docs/data.md runbook)")
_DATA_QUEUE_DEPTH = REGISTRY.gauge(
    "mxnet_data_queue_depth",
    "batches currently buffered in the streaming data plane "
    "(io_pipeline shard queues + window feed), by pipeline role")
_DATA_BATCHES = REGISTRY.counter(
    "mxnet_data_batches_total",
    "batches produced by streaming-data-plane reader workers "
    "(reader throughput; rate vs the fit loop's step rate says "
    "whether the readers keep up)")
_DATA_REBALANCE = REGISTRY.counter(
    "mxnet_data_rebalance_total",
    "shard rebalances after a reader worker died mid-epoch "
    "(remaining shards were requeued onto the survivors)")
_SCAN_WINDOW = REGISTRY.gauge(
    "mxnet_scan_window_steps",
    "train steps per scanned fit-window dispatch (MXNET_SCAN_STEPS; "
    "1 = one dispatch per step)")
_SCAN_WINDOW.set(1)
_COLLECTIVE_BYTES = REGISTRY.counter(
    "mxnet_collective_bytes_total",
    "logical payload bytes moved by gradient-synchronization "
    "collectives, by kind (psum/reduce_scatter/all_gather for the mesh "
    "fused step; kvstore_push/kvstore_pull for the residual per-param "
    "store path)")
_COLLECTIVE_SECONDS = REGISTRY.counter(
    "mxnet_collective_seconds",
    "seconds attributed to gradient-synchronization collectives, by "
    "kind (wall time for the kvstore path; calibrated standalone cost "
    "for collectives fused inside the mesh step program)")
_COLLECTIVE_OPS = REGISTRY.counter(
    "mxnet_collective_ops_total",
    "gradient-synchronization collective operations issued, by kind "
    "(one per bucket per step for the mesh fused step — NOT one per "
    "parameter; that is the point)")


def record_kvstore(op, nbytes, n_ops=1):
    """Account one kvstore push/pull: wire/device payload byte volume."""
    labels = {"op": op}
    _KV_BYTES.inc(int(nbytes), labels=labels)
    _KV_OPS.inc(int(n_ops), labels=labels)


def record_collective(kind, nbytes, seconds=0.0, n=1):
    """Account gradient-synchronization collectives: ``kind`` is the
    collective flavor (``psum``/``reduce_scatter``/``all_gather`` inside
    the mesh fused step, ``kvstore_push``/``kvstore_pull`` on the
    residual store path).  Byte counts are host shape arithmetic — never
    a device sync."""
    labels = {"kind": kind}
    _COLLECTIVE_BYTES.inc(int(nbytes), labels=labels)
    if seconds:
        _COLLECTIVE_SECONDS.inc(float(seconds), labels=labels)
    _COLLECTIVE_OPS.inc(int(n), labels=labels)


def record_io_stage(seconds, nbytes=0):
    """Account one io.stage_batch call (the input-feed staging wait)."""
    _IO_STAGE.observe(seconds)
    if nbytes:
        _IO_STAGE_BYTES.inc(int(nbytes))


def record_scan_window(steps):
    """Record the active scanned-window size (Module._fit_epoch_scan)."""
    _SCAN_WINDOW.set(int(steps))


def record_data_wait(seconds):
    """Account one blocking wait on the streaming data plane (the
    consumer side: assembler ``next()`` or window-feed ``get()``)."""
    _DATA_WAIT.observe(seconds)


def record_data_batches(n=1):
    """Account batches produced by reader workers (throughput)."""
    _DATA_BATCHES.inc(int(n))


def record_data_queue_depth(depth, role="shards"):
    """Publish the current buffered-batch count for one pipeline role
    (``shards`` = reader output queues, ``feed`` = staged windows)."""
    _DATA_QUEUE_DEPTH.set(float(depth), labels={"role": role})


def record_data_rebalance(n=1):
    """Account one dead-reader shard rebalance."""
    _DATA_REBALANCE.inc(int(n))


# -- checkpoint manager registration (weak: managers come and go) ------------
_ckpt_managers = weakref.WeakSet()


def register_checkpoint_manager(manager):
    """Called by CheckpointManager.__init__ so its stats() joins the
    ``checkpoint`` collector (weakly held; close() needs no unhook)."""
    _ckpt_managers.add(manager)


# -- collectors --------------------------------------------------------------
def _serving_snapshot():
    # pull, never import: a process that never served has no serving keys
    mod = sys.modules.get("mxnet_tpu.serving.metrics")
    return mod.stats() if mod is not None else {}


def _serving_samples():
    out = []
    for name, snap in sorted(_serving_snapshot().items()):
        labels = {"server": name}
        lat = snap.get("latency_ms") or {}
        for q in ("p50", "p90", "p99"):
            if lat.get(q) is not None:
                out.append(("mxnet_serving_latency_ms", "gauge",
                            "serving request latency percentile",
                            {**labels, "quantile": q}, lat[q]))
        for key, value in sorted(snap.items()):
            if not isinstance(value, (int, float)) or \
                    isinstance(value, bool):
                continue
            mtype = "counter" if key.endswith("_total") else "gauge"
            out.append((f"mxnet_serving_{key}", mtype,
                        f"serving.stats() {key}", labels, value))
    return out


def _checkpoint_snapshot():
    return {m.directory: m.stats() for m in list(_ckpt_managers)}


def _checkpoint_samples():
    renames = {"saves": "saves_total", "failures": "failures_total",
               "gc_removed": "gc_removed_total"}
    out = []
    for directory, stats in sorted(_checkpoint_snapshot().items()):
        labels = {"directory": directory}
        for key, value in sorted(stats.items()):
            if not isinstance(value, (int, float)) or \
                    isinstance(value, bool):
                continue
            name = renames.get(key, key)
            mtype = "counter" if name.endswith("_total") else "gauge"
            out.append((f"mxnet_checkpoint_{name}", mtype,
                        f"CheckpointManager.stats() {key}", labels, value))
    return out


def _profiler_snapshot():
    from .. import profiler
    return {"dispatch": profiler.dispatch_counts(),
            "counters": profiler.last_counters()}


def _profiler_samples():
    from .. import profiler
    out = []
    for kind, n in sorted(profiler.dispatch_counts().items()):
        out.append(("mxnet_dispatch_total", "counter",
                    "framework-issued XLA computation launches, by kind",
                    {"kind": kind}, n))
    for name, value in sorted(profiler.last_counters().items()):
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            out.append(("mxnet_profiler_counter", "gauge",
                        "last value of each profiler counter lane",
                        {"counter": name}, value))
    return out


def _step_samples():
    bd = _steps.step_breakdown()
    out = [("mxnet_train_steps_total", "counter",
            "fit-loop train steps timed by the step breakdown", {},
            bd["steps"]),
           ("mxnet_train_step_wall_seconds_total", "counter",
            "total fit-loop step wall time", {}, bd["wall_s"]),
           ("mxnet_train_step_lane_seconds_total", "counter",
            "total step time attributed to each lane",
            {"lane": "other"}, bd["other_s"])]
    for lane, total in sorted(bd["lanes"].items()):
        out.append(("mxnet_train_step_lane_seconds_total", "counter",
                    "total step time attributed to each lane",
                    {"lane": lane}, total))
    return out


def _watchdog_samples():
    return [("mxnet_watchdog_fires_total", "counter",
             "hang-watchdog stall dumps written", {}, watchdog.fires())]


REGISTRY.register_collector("serving", _serving_snapshot, _serving_samples)
REGISTRY.register_collector("checkpoint", _checkpoint_snapshot,
                            _checkpoint_samples)
REGISTRY.register_collector("profiler", _profiler_snapshot,
                            _profiler_samples)
REGISTRY.register_collector("step", _steps.step_breakdown, _step_samples)
REGISTRY.register_collector(
    "watchdog",
    lambda: {"fires": watchdog.fires(), "last_dump": watchdog.last_dump()},
    _watchdog_samples)
REGISTRY.register_collector("trace", trace.exemplars)
REGISTRY.register_collector("fleet", fleet._collector_snapshot,
                            fleet._collector_samples)
REGISTRY.register_collector(
    "flight",
    lambda: {"enabled": flight.enabled(),
             "ring_events": len(flight.events()),
             "dumps": flight.dump_count()})
REGISTRY.register_collector("resources", resources._collector_snapshot,
                            resources._collector_samples)
REGISTRY.register_collector("numerics", numerics._collector_snapshot)


def _alerts_collector():
    # summary only (rule pack + full history live at /alerts.json);
    # built lazily so an unarmed process pays one dict
    if not alerts.enabled():
        return {"enabled": False}
    snap = alerts.alerts_json()
    return {"enabled": True, "ticks": snap["ticks"],
            "firing": snap["firing"], "pages": snap["pages"],
            "states": {r["name"]: r["state"] for r in snap["rules"]}}


REGISTRY.register_collector("alerts", _alerts_collector)


def snapshot():
    """Everything, one call: local metric families + serving +
    checkpoint + profiler dispatch lanes + step breakdown + watchdog."""
    return REGISTRY.snapshot()


def prometheus_dump():
    """Prometheus text exposition of :func:`snapshot`'s numeric surface."""
    return REGISTRY.prometheus_dump()


# -- env autostart -----------------------------------------------------------
def _autostart():
    from .. import config as _config
    if _config.get("MXNET_TELEMETRY"):
        enable()
    if _config.get("MXNET_TRACE"):
        trace.enable()
    flight.configure()
    numerics.configure()
    if float(_config.get("MXNET_RESOURCE_SAMPLE_S")) > 0:
        resources.start()
    if float(_config.get("MXNET_ALERTS")) > 0:
        alerts.start()
    port = int(_config.get("MXNET_TELEMETRY_PORT"))
    if port > 0:
        start_exporter(port)


_autostart()
