"""Per-train-step time breakdown: where did the 2.7 ms go?

``Module.fit`` opens a :class:`StepTimer` per fit call; each loop
iteration attributes its wall time to named *lanes*:

* ``data_wait``     — blocking in ``next(data_iter)``
* ``h2d_stage``     — host->device staging of the next batch (io.stage_batch)
* ``step_dispatch`` — host time dispatching forward/backward/update
                      (the fused jit call included)
* ``comm_collective`` — gradient-synchronization time: the wall time of
                      the residual per-param kvstore push/pull loop, or
                      the calibrated standalone cost of the mesh fused
                      step's bucketed collectives (reattributed out of
                      ``step_dispatch`` so the lane sum stays exact)
* ``device_block``  — waiting for device results before metric math
                      (the sync the metric flush forces)
* ``metric_flush``  — host-side metric math after arrays landed
* ``ckpt_block``    — checkpoint snapshot time charged to the train thread

Anything unattributed lands in ``other`` (loop bookkeeping, callbacks) —
``step_breakdown()`` reports it explicitly so the lanes are auditable
against wall time (the acceptance bar: named lanes >= 90% of step wall).

Deep call sites (``update_metric``, ``CheckpointManager.save``) find the
fit loop's timer through a thread-local (``current_step_timer()``), so
the attribution needs no plumbing through the Module API.  When
telemetry is disabled the fit loop gets the shared ``_NULL_TIMER`` whose
lanes are no-op context managers.
"""
from __future__ import annotations

import threading
import time

from . import spans as _spans

LANES = ("data_wait", "h2d_stage", "step_dispatch", "comm_collective",
         "device_block", "metric_flush", "ckpt_block")

_tls = threading.local()
_agg_lock = threading.Lock()
_agg = {"steps": 0, "wall_s": 0.0,
        "lanes": {lane: 0.0 for lane in LANES}, "other_s": 0.0,
        "last": {}}

# filled in by telemetry/__init__
_lane_hist = None
_step_hist = None


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _NullStepTimer:
    """Disabled-telemetry stand-in: every call is a cheap no-op."""

    __slots__ = ()
    active = False

    def lane(self, name):
        return _NULL_CTX

    def add(self, name, seconds):
        pass

    def begin_step(self):
        pass

    def end_step(self, steps=1):
        pass

    def close(self):
        pass


_NULL_TIMER = _NullStepTimer()


class _Lane:
    __slots__ = ("_timer", "_name", "_t0")

    def __init__(self, timer, name):
        self._timer = timer
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.add(self._name, time.perf_counter() - self._t0)
        return False


class StepTimer:
    """Accumulates one fit loop's lane times; folds them into the global
    breakdown (and the registry histograms) at every ``end_step``."""

    active = True

    def __init__(self):
        self._cur = {}
        self._step_start = None
        self._prev = getattr(_tls, "timer", None)
        _tls.timer = self

    def lane(self, name):
        return _Lane(self, name)

    def add(self, name, seconds):
        self._cur[name] = self._cur.get(name, 0.0) + seconds

    def begin_step(self):
        """(Re-)anchor the step wall clock; lane time already accumulated
        (e.g. an epoch-end checkpoint) stays and folds into the next
        step rather than being dropped."""
        self._step_start = time.perf_counter()

    def end_step(self, steps=1):
        """Close out a timed unit covering ``steps`` train steps (1 for
        the per-batch loop; K*M for a scanned window).  Totals accumulate
        un-amortized — the lanes-vs-wall audit stays exact — while
        ``last`` and the histograms record PER-STEP amortized values so
        StepTimeline output and the step-seconds distribution keep
        meaning \"one train step\" at any window size."""
        now = time.perf_counter()
        n = max(1, int(steps))
        if self._step_start is None:
            self._step_start = now
            return
        wall = now - self._step_start
        self._step_start = now
        cur, self._cur = self._cur, {}
        lane_sum = 0.0
        with _agg_lock:
            _agg["steps"] += n
            _agg["wall_s"] += wall
            for lane, dur in cur.items():
                _agg["lanes"][lane] = _agg["lanes"].get(lane, 0.0) + dur
                lane_sum += dur
            _agg["other_s"] += max(0.0, wall - lane_sum)
            _agg["last"] = {"wall_s": wall / n,
                            "lanes": {lane: dur / n
                                      for lane, dur in cur.items()},
                            "window_steps": n}
        if _lane_hist is not None:
            for lane, dur in cur.items():
                _lane_hist.observe(dur / n, labels={"lane": lane})
        if _step_hist is not None:
            _step_hist.observe(wall / n)

    def close(self):
        _tls.timer = self._prev


def step_timer():
    """A live :class:`StepTimer` (telemetry enabled) or the shared no-op
    one; either way it becomes this thread's ``current_step_timer()``."""
    if not _spans.enabled():
        return _NULL_TIMER
    return StepTimer()


def current_step_timer():
    """The fit loop's timer on this thread (``_NULL_TIMER`` outside)."""
    return getattr(_tls, "timer", None) or _NULL_TIMER


def step_breakdown():
    """Accumulated breakdown: steps, total wall, per-lane totals, the
    unattributed remainder, and the last step's split."""
    with _agg_lock:
        return {"steps": _agg["steps"], "wall_s": _agg["wall_s"],
                "lanes": dict(_agg["lanes"]), "other_s": _agg["other_s"],
                "last": {"wall_s": _agg["last"].get("wall_s"),
                         "lanes": dict(_agg["last"].get("lanes", {})),
                         "window_steps": _agg["last"].get(
                             "window_steps", 1)}}


def reset_step_stats():
    with _agg_lock:
        _agg["steps"] = 0
        _agg["wall_s"] = 0.0
        _agg["lanes"] = {lane: 0.0 for lane in LANES}
        _agg["other_s"] = 0.0
        _agg["last"] = {}
