"""Resource accounting plane: device-buffer ledger + host sampler
(ISSUE 13 tentpole, half one).

Everything before this PR measured *work* (dispatches, latencies,
traces); nothing measured *footprint*.  A leaking fit loop, an executor
cache pinning a retired version's buffers, or a checkpoint directory
quietly filling a disk all presented identically: fine until OOM.  Two
instruments close that gap:

* **device-buffer ledger** (:data:`LEDGER`) — subsystems that own
  long-lived device buffers register their byte footprint by
  ``(owner, kind)``: the fused / scanned / mesh train steps account
  their params / optimizer-state / aux / residual carry at every
  (re)build, the serving executor cache accounts each entry at insert
  and decrements at evict, and AOT warmup records per-model compiled
  HBM estimates via ``compiled.memory_analysis()`` where jax exposes
  it.  All byte math is host shape arithmetic (``shape`` x
  ``dtype.itemsize``) — never a device sync.
* **host sampler** (:func:`start` / :func:`sample_now`) — a daemon
  thread (``MXNET_RESOURCE_SAMPLE_S``) samples RSS, open fds, thread
  count and registered checkpoint-dir disk usage into a bounded
  sliding window, and a least-squares estimator over that window
  (:func:`slope_bytes_per_s`) turns the RSS series into a *leak slope*
  — the signal the alert engine's ``rss_slope`` rule and the soak
  harness gate on (docs/observability.md resource catalog).

Export: one ``resources`` telemetry collector feeding
``snapshot()["resources"]``, the ``mxnet_resource_*`` Prometheus
families, and — because collector samples ride
``MetricsRegistry.sample_families()`` — the PR-12 fleet push, so the
leader's ``/fleet.json`` carries every rank's footprint.
"""
from __future__ import annotations

import collections
import logging
import os
import threading
import time

import numpy as np

log = logging.getLogger("mxnet_tpu.telemetry.resources")

_PAGE_SIZE = 4096
try:
    _PAGE_SIZE = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):
    pass


# -- byte math (host-side only, never a device sync) --------------------------
def nbytes(leaf):
    """Byte footprint of one array-like leaf from shape metadata alone;
    0 for leaves without (shape, dtype)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    except (TypeError, ValueError):
        return 0


def pytree_nbytes(tree):
    """Total byte footprint of a nested structure of array-like leaves
    (dicts / lists / tuples walked; NDArray-style ``._data`` unwrapped)."""
    if tree is None:
        return 0
    if isinstance(tree, dict):
        return sum(pytree_nbytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(pytree_nbytes(v) for v in tree)
    inner = getattr(tree, "_data", None)
    if inner is not None and nbytes(tree) == 0:
        return nbytes(inner)
    return nbytes(tree)


# -- device-buffer ledger ------------------------------------------------------
class DeviceLedger:
    """Registered long-lived device-buffer footprints by (owner, kind).

    ``set`` replaces (a train-step rebuild re-states its whole
    footprint), ``add`` accumulates (executor-cache inserts), and
    ``release`` decrements with a floor at zero (evictions must never
    drive a footprint negative even if an entry was never accounted —
    the ledger is an estimator, not an allocator).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = {}   # (owner, kind) -> bytes
        self._hbm = {}       # owner -> {section: bytes} (compiled estimates)

    def set(self, owner, kind, n):
        with self._lock:
            self._entries[(str(owner), str(kind))] = max(0, int(n))

    def add(self, owner, kind, n):
        key = (str(owner), str(kind))
        with self._lock:
            self._entries[key] = max(0, self._entries.get(key, 0) + int(n))

    def release(self, owner, kind, n):
        self.add(owner, kind, -int(n))

    def clear(self, owner=None):
        with self._lock:
            if owner is None:
                self._entries.clear()
                self._hbm.clear()
            else:
                owner = str(owner)
                for key in [k for k in self._entries if k[0] == owner]:
                    del self._entries[key]
                self._hbm.pop(owner, None)

    def note_hbm_estimate(self, owner, sections):
        """Record a compiled program's HBM estimate for ``owner`` —
        ``sections`` is a {section: bytes} dict (arguments / outputs /
        temp / code / total)."""
        clean = {str(k): int(v) for k, v in sections.items()
                 if isinstance(v, (int, float)) and v >= 0}
        if not clean:
            return
        with self._lock:
            self._hbm[str(owner)] = clean

    def total(self):
        with self._lock:
            return sum(self._entries.values())

    def snapshot(self):
        with self._lock:
            owners = {}
            for (owner, kind), n in sorted(self._entries.items()):
                owners.setdefault(owner, {})[kind] = n
            return {"total_bytes": sum(self._entries.values()),
                    "owners": owners,
                    "hbm_estimates": {o: dict(s)
                                      for o, s in sorted(self._hbm.items())}}

    def samples(self):
        with self._lock:
            entries = dict(self._entries)
            hbm = {o: dict(s) for o, s in self._hbm.items()}
        out = [("mxnet_resource_device_total_bytes", "gauge",
                "total registered long-lived device-buffer bytes",
                {}, sum(entries.values()))]
        for (owner, kind), n in sorted(entries.items()):
            out.append(("mxnet_resource_device_bytes", "gauge",
                        "registered device-buffer bytes, by owner and kind",
                        {"owner": owner, "kind": kind}, n))
        for owner, sections in sorted(hbm.items()):
            for section, n in sorted(sections.items()):
                out.append(("mxnet_resource_hbm_estimate_bytes", "gauge",
                            "compiled-program HBM estimate "
                            "(compiled.memory_analysis), by owner/section",
                            {"owner": owner, "section": section}, n))
        return out


LEDGER = DeviceLedger()


def account_train_step(owner, params=(), opt_state=None, aux=(),
                       extra=None):
    """One train-step (re)build states its whole carry footprint:
    params / optimizer state / aux stats, plus any step-specific
    ``extra`` {kind: bytes} (mesh gradient buckets, codec residuals).
    Called at build time only — never on the per-step hot path."""
    LEDGER.set(owner, "params", pytree_nbytes(list(params)))
    LEDGER.set(owner, "opt_state", pytree_nbytes(opt_state))
    LEDGER.set(owner, "aux", pytree_nbytes(list(aux)))
    for kind, n in (extra or {}).items():
        LEDGER.set(owner, kind, n)


def note_compiled(owner, compiled):
    """Record a compiled executable's HBM estimate where jax exposes
    ``memory_analysis()`` (AOT warmup calls this per warmed model);
    silently a no-op on backends/versions that do not."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 — optional introspection; absence is normal on some backends
        log.debug("memory_analysis unavailable for %s: %s", owner, e)
        return None
    sections = {}
    for section, attr in (("arguments", "argument_size_in_bytes"),
                          ("outputs", "output_size_in_bytes"),
                          ("temp", "temp_size_in_bytes"),
                          ("code", "generated_code_size_in_bytes"),
                          ("alias", "alias_size_in_bytes")):
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)) and v >= 0:
            sections[section] = int(v)
    if sections:
        sections["total"] = sum(v for k, v in sections.items()
                                if k != "alias")
        LEDGER.note_hbm_estimate(owner, sections)
    return sections or None


# -- host sampler --------------------------------------------------------------
def read_rss_bytes():
    """Current resident set size.  /proc on Linux; best-effort (peak
    RSS via getrusage) elsewhere — the slope estimator only needs a
    consistent series, and 0 simply disables the leak signal."""
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024
    except Exception:  # graftlint: disable=swallowed-error -- best-effort sampling; 0 disables the leak signal cleanly
        return 0


def read_open_fds():
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def dir_bytes(path):
    """Recursive byte usage of a directory (best-effort; races with
    concurrent GC/commits are fine — this is a trend signal)."""
    total = 0
    try:
        for dirpath, _dirnames, filenames in os.walk(path):
            for fn in filenames:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    continue
    except OSError:
        return 0
    return total


def slope_bytes_per_s(points):
    """Least-squares slope of an ``[(t_seconds, bytes), ...]`` series —
    the leak estimator.  Returns 0.0 for fewer than 3 points or a
    degenerate (zero-span) time axis, so startup noise never fabricates
    a leak."""
    if len(points) < 3:
        return 0.0
    ts = np.asarray([p[0] for p in points], np.float64)
    ys = np.asarray([p[1] for p in points], np.float64)
    ts = ts - ts[0]
    span = float(ts[-1])
    if span <= 0:
        return 0.0
    t_mean = ts.mean()
    denom = float(((ts - t_mean) ** 2).sum())
    if denom <= 0:
        return 0.0
    return float(((ts - t_mean) * (ys - ys.mean())).sum() / denom)


class HostSampler:
    """Sliding-window host resource sampler.  ``sample_now()`` is also
    callable directly (the collector takes one on-demand sample when no
    thread is running, and the bench phase times it)."""

    def __init__(self, window=240):
        self._lock = threading.Lock()
        self._window = collections.deque(maxlen=max(8, int(window)))
        self._thread = None
        self._stop = None
        self._samples = 0
        self.interval_s = 0.0

    def _ckpt_dirs(self):
        from . import _ckpt_managers
        dirs = []
        for mgr in list(_ckpt_managers):
            d = getattr(mgr, "directory", None)
            if d:
                dirs.append(str(d))
        return sorted(set(dirs))

    def sample_now(self, rss=None, t=None, disk=True):
        """Take one sample (synthetic ``rss``/``t`` overrides keep the
        leak-slope tests deterministic); returns the sample dict."""
        entry = {
            "t": time.monotonic() if t is None else float(t),
            "rss_bytes": read_rss_bytes() if rss is None else int(rss),
            "open_fds": read_open_fds(),
            "threads": threading.active_count(),
            "ckpt_disk_bytes": {},
        }
        if disk:
            for d in self._ckpt_dirs():
                entry["ckpt_disk_bytes"][d] = dir_bytes(d)
        with self._lock:
            self._window.append(entry)
            self._samples += 1
        return entry

    def leak_slope(self):
        """RSS leak slope (bytes/s) over the current window."""
        with self._lock:
            pts = [(e["t"], e["rss_bytes"]) for e in self._window
                   if e["rss_bytes"] > 0]
        return slope_bytes_per_s(pts)

    def last(self):
        with self._lock:
            return dict(self._window[-1]) if self._window else None

    def reset(self):
        with self._lock:
            self._window.clear()
            self._samples = 0

    def running(self):
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def start(self, interval_s):
        """Start (or retune) the sampling thread; 0 stops it."""
        interval_s = float(interval_s)
        if interval_s <= 0:
            self.stop()
            return
        with self._lock:
            self.interval_s = interval_s
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="mx-resource-sampler")
            self._thread.start()

    def stop(self):
        with self._lock:
            stop, self._stop = self._stop, None
            thread, self._thread = self._thread, None
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=5)

    def _loop(self):
        while True:
            with self._lock:
                stop = self._stop
                interval = self.interval_s
            if stop is None or stop.wait(max(0.01, interval)):
                return
            try:
                self.sample_now()
            except Exception as e:  # noqa: BLE001 — one failed sample must not kill the sampler
                log.debug("resource sample failed: %s", e)


SAMPLER = HostSampler()


def start(interval_s=None):
    """Arm the host sampler (MXNET_RESOURCE_SAMPLE_S default)."""
    if interval_s is None:
        from .. import config as _config
        interval_s = float(_config.get("MXNET_RESOURCE_SAMPLE_S"))
    SAMPLER.start(interval_s)
    return SAMPLER.running()


def stop():
    SAMPLER.stop()


def sample_now(**kw):
    return SAMPLER.sample_now(**kw)


def leak_slope():
    return SAMPLER.leak_slope()


# -- telemetry collector hooks -------------------------------------------------
def _collector_snapshot():
    last = SAMPLER.last()
    if last is None:
        # no sampler thread and nobody sampled yet: one on-demand
        # sample keeps /snapshot.json meaningful on any process (no
        # history -> slope reads 0, never a fabricated leak)
        last = SAMPLER.sample_now()
    out = {"device": LEDGER.snapshot(),
           "host": dict(last),
           "rss_slope_bytes_per_s": SAMPLER.leak_slope(),
           "sampler_running": SAMPLER.running(),
           "samples": SAMPLER._samples}
    return out


def _collector_samples():
    out = list(LEDGER.samples())
    last = SAMPLER.last() or SAMPLER.sample_now()
    out.append(("mxnet_resource_rss_bytes", "gauge",
                "resident set size at the last host sample", {},
                last["rss_bytes"]))
    out.append(("mxnet_resource_open_fds", "gauge",
                "open file descriptors at the last host sample", {},
                last["open_fds"]))
    out.append(("mxnet_resource_threads", "gauge",
                "live threads at the last host sample", {},
                last["threads"]))
    for d, n in sorted(last.get("ckpt_disk_bytes", {}).items()):
        out.append(("mxnet_resource_ckpt_disk_bytes", "gauge",
                    "disk bytes under each registered checkpoint "
                    "directory", {"directory": d}, n))
    out.append(("mxnet_resource_rss_slope_bytes_per_s", "gauge",
                "least-squares RSS slope over the sampler window "
                "(the leak estimator)", {}, SAMPLER.leak_slope()))
    out.append(("mxnet_resource_samples_total", "counter",
                "host resource samples taken", {}, SAMPLER._samples))
    return out
