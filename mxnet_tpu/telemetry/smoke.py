"""CI telemetry smoke (run via ``python -m mxnet_tpu.telemetry.smoke``).

Exercises the whole observability surface the way an operator would:

1. telemetry + exporter on (ephemeral port), watchdog armed with a
   generous timeout (it must stay SILENT through a healthy run);
2. a 5-step ``Module.fit`` (step-lane breakdown), a serving burst
   through the DynamicBatcher, and one checkpoint commit;
3. ``telemetry.snapshot()`` must carry all four subsystems from ONE
   call; the scraped ``/metrics`` endpoint must be valid Prometheus
   exposition text containing the required metric families;
4. the step-breakdown lanes must account for >= 90% of measured step
   wall time, and the watchdog must not have fired.
"""
from __future__ import annotations

import os
import re
import sys
import tempfile
import urllib.request

os.environ.setdefault("MXNET_TELEMETRY", "1")
os.environ.setdefault("MXNET_TRACE", "1")
os.environ.setdefault("MXNET_WATCHDOG_S", "120")

REQUIRED_FAMILIES = (
    "mxnet_train_step_lane_seconds_total",
    "mxnet_train_steps_total",
    "mxnet_serving_requests_total",
    "mxnet_serving_responses_total",
    "mxnet_dispatch_total",
    "mxnet_checkpoint_saves_total",
    "mxnet_span_seconds",
    "mxnet_watchdog_fires_total",
    "mxnet_trace_stage_seconds",
    "mxnet_trace_e2e_seconds",
    "mxnet_resource_rss_bytes",
    "mxnet_resource_device_total_bytes",
    "mxnet_resource_device_bytes",
)

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$")


def _fail(msg):
    print(f"FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main():
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    telemetry.enable()
    # `python -m` imports the telemetry package before this module's
    # env defaults land, so arm the ISSUE-12 planes explicitly too
    telemetry.trace.enable()
    telemetry.flight.enable()
    port = telemetry.start_exporter(0)
    print(f"exporter on http://127.0.0.1:{port}/metrics")

    def build(train=True):
        d = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(d, num_hidden=64, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
        # serving wants the label-free graph (logits); fit wants the loss
        return mx.sym.SoftmaxOutput(h, name="softmax") if train else h

    # -- 5-step fit (one epoch over 5 batches) ------------------------------
    rng = np.random.RandomState(0)
    x = rng.randn(160, 50).astype(np.float32)
    y = rng.randint(0, 10, 160).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=32)
    mod = mx.mod.Module(build(), context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            batch_end_callback=mx.callback.StepTimeline(frequent=3))

    # -- serving burst -------------------------------------------------------
    with mx.serving.ModelServer(max_latency_ms=2.0) as server:
        server.load("mlp", symbol=build(train=False),
                    params={"fc1_weight": mx.nd.array(
                                rng.randn(64, 50).astype(np.float32) * 0.1),
                            "fc1_bias": mx.nd.zeros((64,)),
                            "fc2_weight": mx.nd.array(
                                rng.randn(10, 64).astype(np.float32) * 0.1),
                            "fc2_bias": mx.nd.zeros((10,))})
        futs = [server.predict_async(
                    "mlp", {"data": rng.randn(50).astype(np.float32)})
                for _ in range(48)]
        for f in futs:
            f.result(30.0)

    # -- one checkpoint commit ----------------------------------------------
    from mxnet_tpu.checkpoint import CheckpointManager
    with tempfile.TemporaryDirectory() as tmp:
        with CheckpointManager(tmp) as mgr:
            mgr.save(1, arrays={"w": mx.nd.ones((8, 8))}, block=True)
            if mgr.stats()["saves"] != 1:
                _fail("checkpoint save not visible in stats()")

        # -- one snapshot, four subsystems ----------------------------------
        snap = telemetry.snapshot()
        if not snap["serving"]:
            _fail("snapshot() has no serving metrics")
        responses = max((s.get("responses_total", 0)
                         for s in snap["serving"].values()), default=0)
        if responses < 48:
            _fail(f"serving responses missing from snapshot: "
                  f"{snap['serving']}")
        if not snap["checkpoint"]:
            _fail("snapshot() has no checkpoint metrics")
        if snap["profiler"]["dispatch"].get("total", 0) < 5:
            _fail("snapshot() has no fused-step dispatch counts")
        step = snap["step"]
        if step["steps"] < 5:
            _fail(f"snapshot() step breakdown saw {step['steps']} steps")
        lane_cover = sum(step["lanes"].values()) / max(1e-9, step["wall_s"])
        print(f"step lanes cover {lane_cover:.1%} of wall "
              f"({step['steps']} steps)")
        if lane_cover < 0.9:
            _fail(f"step lanes cover only {lane_cover:.1%} of wall time")

        # -- resource observatory (ISSUE 13): the fused fit registered
        # its carry footprint and the serving burst its executors -----
        res = snap.get("resources", {})
        owners = res.get("device", {}).get("owners", {})
        if owners.get("fused_step", {}).get("params", 0) <= 0:
            _fail(f"fused step registered no param footprint: {owners}")
        if not any("executor_cache" in kinds for kinds in owners.values()):
            _fail(f"executor cache registered no footprint: {owners}")
        if res.get("host", {}).get("rss_bytes", 0) <= 0:
            _fail(f"host sampler produced no RSS sample: {res.get('host')}")

        # -- trace exemplars (ISSUE 12): every served request traced,
        # stage spans covering >=95% of the measured e2e latency --------
        traces = snap.get("trace", {}).get("serving")
        if not traces or traces["count"] < 48:
            _fail(f"serving traces missing from snapshot: {traces}")
        worst = (traces["slowest"] or [traces["last"]])[0]
        if worst["coverage"] < 0.95:
            _fail(f"slowest request's stage spans cover only "
                  f"{worst['coverage']:.1%} of its e2e latency: {worst}")
        if traces["count"] and snap.get("flight", {}).get(
                "enabled") is not True:
            _fail("flight recorder not live during the smoke")

        # -- scrape ----------------------------------------------------------
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            text = resp.read().decode("utf-8")
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if not _SAMPLE_RE.match(line):
            _fail(f"invalid exposition line: {line!r}")
    for family in REQUIRED_FAMILIES:
        if f"# TYPE {family} " not in text:
            _fail(f"metric family {family} missing from /metrics scrape")

    # -- watchdog stayed silent ----------------------------------------------
    if telemetry.watchdog.fires() != 0:
        _fail(f"watchdog fired {telemetry.watchdog.fires()} time(s) "
              f"during a healthy run ({telemetry.watchdog.last_dump()})")

    telemetry.stop_exporter()
    print("telemetry smoke OK: snapshot unified 4 subsystems, "
          f"{len(REQUIRED_FAMILIES)} families scraped, lanes {lane_cover:.0%}"
          f" of step wall, {traces['count']} request traces at "
          f">=95% stage coverage (slowest {worst['coverage']:.0%}), "
          "watchdog silent")


if __name__ == "__main__":
    main()
