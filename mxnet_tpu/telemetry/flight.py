"""Crash flight recorder: a bounded ring of structured decision events,
dumped atomically when the process dies messily (ISSUE 12 tentpole).

A dead generation used to leave scattered stderr and watchdog files;
"why did generation 0 die" was archaeology.  The flight recorder turns
every subsystem's *decision points* into ring entries — state
transitions, sheds, spills, chaos injections, worker restarts,
rendezvous outcomes, peer-loss marks, checkpoint commits — each a
``{seq, t, mono, thread, category, event, severity, fields}`` record
appended under one cheap lock into a bounded deque
(``MXNET_FLIGHT_RING`` events, oldest evicted).

The ring is dumped atomically (tmp + ``os.replace``) as
``mxnet-flight-<pid>-<n>.json`` into ``MXNET_FLIGHT_DIR`` (or
``MXNET_WATCHDOG_DIR``, or cwd) on:

* a **watchdog fire** (the stall dump and the event history land
  together);
* a **typed-fatal elastic fault** (PeerLostError / PreemptionError —
  the worker dumps before taking its restart/leave exit);
* **SIGTERM** (the multi-host preemption notice);
* a **chaos ``kill``** arm — the ring is flushed *before* the SIGKILL
  lands, so even a vanished host leaves its story behind.

The :class:`~mxnet_tpu.parallel.elastic.ElasticLauncher` points each
worker generation's ``MXNET_FLIGHT_DIR`` at a harvest directory and,
after a fault, folds all ranks' rings + watchdog dumps + the final
fleet snapshot into ONE postmortem bundle (docs/observability.md
runbook).

``MXNET_FLIGHT=0`` reduces :func:`record` to a single module-global
check (< 1 µs, the chaos-failpoint bar), so the hooks stay wired into
hot paths unconditionally.  Dump files obey the shared
``MXNET_WATCHDOG_KEEP`` retention (newest N kept).
"""
from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time

log = logging.getLogger("mxnet_tpu.telemetry.flight")

# module-global fast gate: the ONLY thing a disabled record() touches
_armed = True

_lock = threading.Lock()
_ring = collections.deque(maxlen=1024)
_seq = 0
_dumps = 0

SEVERITIES = ("info", "warn", "error")


def configure(enabled=None, ring=None):
    """(Re)configure from the env knobs — called at telemetry import;
    tests flip :func:`enable` / :func:`disable` directly."""
    global _armed, _ring
    from .. import config as _config
    if enabled is None:
        enabled = bool(_config.get("MXNET_FLIGHT"))
    if ring is None:
        ring = int(_config.get("MXNET_FLIGHT_RING"))
    with _lock:
        if ring != _ring.maxlen:
            _ring = collections.deque(_ring, maxlen=max(16, ring))
    _armed = bool(enabled)


def enable():
    global _armed
    _armed = True


def disable():
    global _armed
    _armed = False


def enabled():
    return _armed


def _native(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_native(x) for x in v]
    item = getattr(v, "item", None)
    if callable(item) and getattr(v, "ndim", 1) == 0:
        try:
            return item()
        except Exception:  # graftlint: disable=swallowed-error -- best-effort coercion; the str fallback below always works
            pass
    return str(v)


def record(category, event, severity="info", **fields):
    """Append one decision event to the ring (no-op when disabled).

    ``severity``: ``info`` for normal transitions, ``warn``/``error``
    for anomalies — the postmortem reader's "first anomalous event" is
    the first non-info entry across all ranks' merged rings."""
    if not _armed:
        return
    global _seq
    entry = {
        "t": time.time(),
        "mono": time.monotonic(),
        "thread": threading.current_thread().name,
        "category": str(category),
        "event": str(event),
        "severity": severity if severity in SEVERITIES else "info",
        "fields": {k: _native(v) for k, v in fields.items()},
    }
    with _lock:
        _seq += 1
        entry["seq"] = _seq
        _ring.append(entry)


def events():
    """The ring's current contents, oldest first."""
    with _lock:
        return list(_ring)


def clear():
    global _seq
    with _lock:
        _ring.clear()
        _seq = 0


def dump_count():
    with _lock:
        return _dumps


# -- dumping ------------------------------------------------------------------
def _keep():
    from .. import config as _config
    return int(_config.get("MXNET_WATCHDOG_KEEP"))


def dump_dir():
    from .. import config as _config
    return (_config.get("MXNET_FLIGHT_DIR")
            or _config.get("MXNET_WATCHDOG_DIR") or os.getcwd())


def prune(directory, prefix, keep=None):
    """Shared dump retention (MXNET_WATCHDOG_KEEP): keep the newest
    ``keep`` files matching ``prefix*`` in ``directory``, remove the
    rest.  Best-effort — retention must never fail the dump."""
    keep = _keep() if keep is None else int(keep)
    if keep <= 0:
        return []
    try:
        names = [n for n in os.listdir(directory) if n.startswith(prefix)
                 and not n.endswith(".tmp")]
    except OSError:
        return []
    paths = []
    for n in names:
        p = os.path.join(directory, n)
        try:
            paths.append((os.path.getmtime(p), p))
        except OSError:
            continue
    paths.sort(reverse=True)
    removed = []
    for _mt, p in paths[keep:]:
        try:
            os.remove(p)
            removed.append(p)
        except OSError as e:
            log.debug("flight: retention could not remove %s: %s", p, e)
    return removed


def dump(path=None, reason=""):
    """Write the ring atomically as JSON; returns the path.  The
    payload carries enough identity (pid, rank, generation env) for the
    launcher's postmortem merge."""
    global _dumps
    with _lock:
        _dumps += 1
        n = _dumps
        ring = list(_ring)
    if path is None:
        directory = dump_dir()
        path = os.path.join(directory,
                            f"mxnet-flight-{os.getpid()}-{n}.json")
    else:
        directory = os.path.dirname(os.path.abspath(path))
    payload = {
        "pid": os.getpid(),
        "rank": os.environ.get("MXNET_MULTIHOST_PROC_ID"),
        "world": os.environ.get("MXNET_MULTIHOST_NUM_PROCS"),
        "reason": str(reason),
        "time": time.time(),
        "events": ring,
    }
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    prune(directory, "mxnet-flight-")
    return path


def auto_dump(reason):
    """Best-effort dump for the fatal paths (watchdog fire, typed-fatal
    error, SIGTERM, chaos kill) — logging, never raising: the dump must
    not mask the event that triggered it."""
    if not _armed:
        return None
    try:
        path = dump(reason=reason)
        log.error("flight recorder dumped (%s) -> %s", reason, path)
        return path
    except Exception as e:  # noqa: BLE001 — the triggering fault outranks the dump
        log.error("flight recorder dump failed (%s): %s", reason, e)
        return None


def first_anomaly(rings):
    """Across one or more dumped rings (each a payload dict or raw
    event list), the earliest non-info event by wall time — the
    postmortem reader's "start here" pointer."""
    merged = []
    for ring in rings:
        evs = ring.get("events", []) if isinstance(ring, dict) else ring
        merged.extend(e for e in evs if e.get("severity") != "info")
    merged.sort(key=lambda e: e.get("t", 0.0))
    return merged[0] if merged else None
