"""Cross-rank telemetry aggregation: one fleet snapshot for N processes
(ISSUE 12 tentpole; sharded/sublinear plane: ISSUE 20).

PR 11 made training multi-process, but each rank still kept its own
PR-5 registry — an operator (or the ROADMAP item-4 autoscaler) had to
scrape N exporters and join them by hand, and a dead rank's metrics
simply vanished.  This module closes that gap over the transport that
already exists:

* **rank side** — :class:`FleetReporter` (armed by the multi-host
  runtime when ``MXNET_FLEET_INTERVAL_S`` > 0) pushes the registry's
  flattened sample families (:meth:`MetricsRegistry.sample_families`)
  to the control-plane kvstore server on its OWN connection (a barrier
  blocking the main RPC socket must not stall telemetry), every
  interval and once more at shutdown/fault.  With ``MXNET_FLEET_DELTA``
  (default on) pushes are **delta-encoded** against the last snapshot
  the server acked (:class:`~.registry.SampleDeltaEncoder`): an
  unchanged family costs ~0 wire bytes and ~0 merge work;
* **server side** — the :class:`~mxnet_tpu.kvstore_server.KVServer`
  delegates to a :class:`FleetStore`: a sharded, incrementally-upserted
  per-``(generation, rank)`` store.  A push touches only its changed
  families (merge cost O(changed), not O(ranks × families)) while
  fleet-wide family aggregates and per-rule alert state VECTORS are
  maintained in the same pass, so the rollup needs no per-rank scan;
* **leader side** — :func:`merge_server` joins the store with the
  server's liveness layer.  Two scrape contracts: ``detail="rank"``
  (the pre-ISSUE-20 full view, byte-compatible: per-rank families,
  per-generation history — automatic at world ≤ 8) and ``"summary"``
  (automatic above 8 ranks): O(families + anomalous ranks) — peer
  counts, the aggregated family catalog, the vectorized alert rollup
  and ONLY the non-alive ranks, served from a bounded-staleness cache.
  A dead rank keeps its last snapshot tagged ``state="lost"`` — never
  silently dropped — and retained generations (capped by
  ``MXNET_FLEET_HISTORY``, with an absence-safe truncation marker) keep
  their per-rank families, so "what was rank 1 doing when it died"
  still reads off ``/fleet.json?detail=rank``.

Serving surfaces: the exporter's ``GET /fleet.json`` renders
:func:`fleet_json` (the registered provider on the leader, a local
single-rank view elsewhere), and the ``fleet`` telemetry collector
re-emits rank samples into the Prometheus dump (full rank-labelled
re-emit in detail mode; summary families only at scale).  The plane
watches itself: ``mxnet_fleet_merge_seconds`` /
``mxnet_fleet_rollup_seconds`` / ``mxnet_fleet_push_bytes{mode}`` feed
the ``fleet_merge_slow`` alert rule, and
``mxnet_tpu.telemetry.fleet_sim`` replays the whole plane at 1000
ranks in-process (docs/observability.md "fleet at scale").
"""
from __future__ import annotations

import logging
import pickle
import threading
import time

log = logging.getLogger("mxnet_tpu.telemetry.fleet")

_provider_lock = threading.Lock()
_provider = None   # callable -> fleet snapshot dict (the leader)

# world size at or below which /fleet.json defaults to the full
# (pre-ISSUE-20, byte-compatible) per-rank view; above it the summary
# contract keeps the scrape O(families + anomalous ranks)
DETAIL_AUTO_RANKS = 8

# bounded staleness of the cached summary rollup: repeated scrapes
# within this window re-serve the same aggregation (the store version
# also invalidates it, so an idle fleet never recomputes at all)
ROLLUP_STALENESS_S = 0.5


def _registry():
    from . import REGISTRY
    return REGISTRY


def local_payload():
    """This rank's pushable snapshot: flattened sample families plus a
    wall-clock stamp (all leaves JSON-native)."""
    return {"time": time.time(),
            "families": _registry().sample_families()}


# -- self-observability (ISSUE 20 satellite) ----------------------------------
def _merge_hist():
    return _registry().histogram(
        "mxnet_fleet_merge_seconds",
        "leader-side cost of applying ONE rank's telemetry push into "
        "the fleet store (O(changed families) with delta pushes)",
        buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 2e-2, 1e-1, 1.0))


def _rollup_hist():
    return _registry().histogram(
        "mxnet_fleet_rollup_seconds",
        "leader-side cost of building one /fleet.json view (summary "
        "views are cached for ROLLUP_STALENESS_S)",
        buckets=(1e-4, 5e-4, 1e-3, 5e-3, 2e-2, 5e-2, 2e-1, 1.0, 5.0))


def _push_bytes_counter():
    return _registry().counter(
        "mxnet_fleet_push_bytes",
        "rank-side serialized telemetry push bytes by encoding mode "
        "(delta pushes of an idle registry should be near zero)")


def _push_failpoint():
    from ..chaos.failpoints import failpoint
    failpoint("fleet/push")


# -- rank side ----------------------------------------------------------------
class FleetReporter:
    """Daemon thread pushing this rank's registry snapshot to the
    control-plane server every ``interval_s``; ``push_now()`` forces a
    final push on the fault/shutdown paths.  ``delta=None`` follows
    ``MXNET_FLEET_DELTA``."""

    def __init__(self, host, port, rank, world, interval_s, timeout=10.0,
                 delta=None):
        self.rank = int(rank)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._client = None
        self._host, self._port = host, int(port)
        self._world = int(world)
        self._timeout = float(timeout)
        if delta is None:
            from ..config import get as _cfg
            delta = bool(_cfg("MXNET_FLEET_DELTA"))
        if delta:
            from .registry import SampleDeltaEncoder
            self._encoder = SampleDeltaEncoder()
        else:
            self._encoder = None
        # the loop thread and the stop()/fault path share ONE client
        # socket and ONE delta encoder: pushes must serialize or
        # interleaved RPC frames / out-of-order seqs garble a push
        self._push_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="mx-fleet-reporter")
        self._thread.start()

    def _ensure_client(self):
        if self._client is None:
            from ..kvstore_server import KVClient
            self._client = KVClient(
                self._host, self._port, rank=self.rank,
                num_workers=self._world, timeout=self._timeout,
                heartbeat_interval=0)
        return self._client

    def _loop(self):
        # first push immediately: a rank killed early must still appear
        # in the fleet snapshot (lost, not vanished)
        while True:
            try:
                self.push_now()
            except Exception as e:  # noqa: BLE001 — telemetry push failures age the snapshot; they must not kill the reporter
                log.debug("fleet reporter push failed: %s", e)
                if self._stop.is_set():
                    return
            if self._stop.wait(self.interval_s):
                return

    def push_now(self):
        """One synchronous push (used by the loop and the fault path;
        the lock serializes the two callers)."""
        with self._push_lock:
            client = self._ensure_client()
            payload = local_payload()
            if self._encoder is not None:
                payload = self._encoder.encode(payload)
            _push_failpoint()
            resp = client.push_telemetry(payload) or {}
            if self._encoder is not None and resp.get("resync"):
                # the server forgot this rank's baseline (restart, lost
                # ack, generation bump): exactly ONE full push resyncs
                self._encoder.reset()
                payload = self._encoder.encode(local_payload())
                resp = client.push_telemetry(payload) or {}
            if self._encoder is not None and \
                    resp.get("acked") is not None:
                self._encoder.ack(resp["acked"])
            self._record_push(payload, client)

    def _record_push(self, payload, client=None):
        try:
            mode = "delta" if "delta" in payload else "full"
            nbytes = None
            if client is not None:
                last = getattr(client, "last_sent_bytes", None)
                if last is not None:
                    # the RPC already serialized the push — read the
                    # wire frame size instead of re-pickling the payload
                    nbytes = last()
            if nbytes is None:
                nbytes = len(pickle.dumps(
                    payload, protocol=pickle.HIGHEST_PROTOCOL))
            _push_bytes_counter().inc(nbytes, labels={"mode": mode})
        except Exception as e:  # noqa: BLE001 — accounting must not fail the push path
            log.debug("fleet push accounting failed: %s", e)

    def stop(self, final_push=True):
        self._stop.set()
        if final_push:
            try:
                self.push_now()
            except Exception as e:  # noqa: BLE001 — best-effort final sample on a possibly-dead transport
                log.debug("fleet reporter final push failed: %s", e)
        if self._client is not None:
            try:
                self._client.close()
            except Exception:  # graftlint: disable=swallowed-error -- best-effort teardown on a possibly-dead transport
                pass


# -- server side: the sharded incremental store -------------------------------
def _fam_stats(fam):
    """(sample count, numeric value sum) of one sample family — the
    per-family contribution to the fleet-wide aggregate catalog."""
    n = 0
    total = 0.0
    for sample in fam.get("values", ()):
        n += 1
        v = sample.get("value")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            total += v
    return n, total


def _alert_vector(fam):
    """One rank's ``mxnet_alert_state`` family reduced to its per-rule
    state vector ``{"rules": {rule: state}, "firing": [rule, ...]}``
    (sample order preserved — the rollup renders it back verbatim)."""
    rules = {}
    firing = []
    for sample in fam.get("values", ()):
        if sample.get("value") != 1:
            continue
        labels = sample.get("labels", {})
        rule, state = labels.get("rule"), labels.get("state")
        if not rule or not state:
            continue
        rules[rule] = state
        if state == "firing":
            firing.append(rule)
    if not rules:
        return None
    return {"rules": rules, "firing": firing}


class FleetStore:
    """Sharded, incrementally-upserted per-``(generation, rank)``
    telemetry store — the leader-side half of the delta push protocol
    (ISSUE 20 tentpole).

    Replaces the KVServer's flat ``_telemetry`` dict + full re-merge:

    * ranks are sharded across ``shards`` locks, so 1000 concurrently
      pushing ranks never serialize on one mutex;
    * :meth:`apply_push` decodes a full or delta payload and upserts
      ONLY the changed families into the rank's retained family dict —
      O(changed families) per push — while maintaining, in the same
      pass, the fleet-wide family catalog aggregates and the per-rank
      alert state vectors the summary rollup renders without any
      per-rank dict scan;
    * a delta whose ``base`` does not match the stored ``seq`` (server
      restart, lost ack, generation bump) is refused with
      ``{"resync": True}`` — the rank answers with one full push; so
      is any push for a non-current generation (it raced
      ``reset_world``): applying it would resurrect a pruned
      generation into retained history;
    * retained generations are capped at ``MXNET_FLEET_HISTORY``
      (:meth:`set_generation` prunes; ``dropped_generations`` feeds the
      absence-safe truncation marker in the detail view).

    ``clock`` (default ``time.monotonic``) stamps snapshot ages; the
    fleet simulator injects a virtual clock so a 1000-rank, 50-cycle
    run completes in seconds.
    """

    def __init__(self, clock=None, shards=16, history=None,
                 generation=0):
        self._clock = clock if clock is not None else time.monotonic
        if history is None:
            from ..config import get as _cfg
            history = int(_cfg("MXNET_FLEET_HISTORY"))
        self.history_cap = max(1, int(history))
        self._nshards = max(1, int(shards))
        self._shard_locks = [threading.Lock()
                             for _ in range(self._nshards)]
        self._meta = threading.Lock()   # generation-map structure
        # gen -> [shard dict, ...]; the initial generation's shards
        # exist from birth so apply_push's not-in-_gens refusal never
        # bounces a non-elastic world's first push into a resync loop
        self._gens = {int(generation): [
            {} for _ in range(self._nshards)]}
        self._dropped_gens = 0
        # current-generation aggregates (all under _agg_lock)
        self._agg_lock = threading.Lock()
        self._generation = int(generation)
        self._families = {}   # family -> {type, ranks, samples, sum}
        self._alerts = {}     # rank -> {"rules": {...}, "firing": [...]}
        self._version = 0
        self._counts = {"pushes": 0, "full": 0, "delta": 0, "resync": 0}
        self._cache = None    # (version, built_mono, summary dict)

    # -- structure ----------------------------------------------------------
    def _gen_shards(self, gen):
        with self._meta:
            shards = self._gens.get(gen)
            if shards is None:
                shards = self._gens[gen] = [
                    {} for _ in range(self._nshards)]
            return shards

    def set_generation(self, gen):
        """Re-arm for a new elastic world generation: aggregates reset
        (they describe the CURRENT generation only; ranks repopulate
        them on their next push — a delta against a pre-bump baseline
        resyncs), retained generations pruned to ``history_cap``."""
        gen = int(gen)
        self._gen_shards(gen)
        with self._meta:
            for old in sorted(self._gens)[:-self.history_cap]:
                del self._gens[old]
                self._dropped_gens += 1
        with self._agg_lock:
            self._generation = gen
            self._families = {}
            self._alerts = {}
            self._version += 1
            self._cache = None

    def dropped_generations(self):
        with self._meta:
            return self._dropped_gens

    def retained_generations(self):
        with self._meta:
            return sorted(self._gens)

    # -- write path ---------------------------------------------------------
    def apply_push(self, generation, rank, payload):
        """Decode + upsert one rank's push; returns the wire reply
        (``{"ok", "acked", "mode"}`` or ``{"ok", "resync"}``)."""
        t0 = time.perf_counter()
        rank = int(rank)
        payload = payload or {}
        with self._agg_lock:
            current = self._generation
        with self._meta:
            shards = self._gens.get(generation)
        if generation != current or shards is None:
            # a push that raced reset_world (read the old generation
            # before the bump) or targets a pruned one: refuse rather
            # than resurrect a near-empty generation into retained
            # history — the rank answers with one full push at the
            # generation it reads next
            with self._agg_lock:
                self._counts["resync"] += 1
            return {"ok": True, "resync": True}
        sh = rank % self._nshards
        with self._shard_locks[sh]:
            entry = shards[sh].get(rank)
            delta = payload.get("delta")
            if delta is not None:
                # decide the refusal BEFORE creating the entry: a
                # refused delta must not leave an empty placeholder
                # (mono=None) that a concurrent detail merge trips on
                if entry is None or entry["seq"] is None or \
                        entry["seq"] != delta.get("base"):
                    with self._agg_lock:
                        self._counts["resync"] += 1
                    return {"ok": True, "resync": True}
            if entry is None:
                entry = shards[sh][rank] = {
                    "families": {}, "stats": {}, "seq": None,
                    "mono": None, "time": None}
            if delta is not None:
                mode = "delta"
                changed = delta.get("changed") or {}
                removed = delta.get("removed") or ()
                entry["seq"] = delta.get("seq")
            else:
                mode = "full"
                changed = payload.get("families") or {}
                removed = [f for f in entry["families"]
                           if f not in changed]
                entry["seq"] = payload.get("seq")
            fams, stats = entry["families"], entry["stats"]
            agg_delta = []      # (family, type, dn, dsum, dranks)
            alert_vec = ...     # sentinel: untouched
            for f in removed:
                old = stats.pop(f, None)
                fams.pop(f, None)
                if old is not None:
                    agg_delta.append((f, None, -old[0], -old[1], -1))
                if f == "mxnet_alert_state":
                    alert_vec = None
            for f, fam in changed.items():
                old = stats.get(f)
                n, s = _fam_stats(fam)
                stats[f] = (n, s)
                fams[f] = fam
                agg_delta.append((
                    f, fam.get("type"),
                    n - (old[0] if old else 0),
                    s - (old[1] if old else 0.0),
                    0 if old else 1))
                if f == "mxnet_alert_state":
                    alert_vec = _alert_vector(fam)
            entry["mono"] = self._clock()
            entry["time"] = payload.get("time")
            acked = entry["seq"]
        with self._agg_lock:
            if generation == self._generation:
                catalog = self._families
                for f, ftype, dn, dsum, dranks in agg_delta:
                    agg = catalog.get(f)
                    if agg is None:
                        if dranks <= 0:
                            continue
                        agg = catalog[f] = {
                            "type": ftype or "gauge", "ranks": 0,
                            "samples": 0, "sum": 0.0}
                    agg["ranks"] += dranks
                    agg["samples"] += dn
                    agg["sum"] += dsum
                    if agg["ranks"] <= 0:
                        del catalog[f]
                if alert_vec is not ...:
                    if alert_vec is None:
                        self._alerts.pop(rank, None)
                    else:
                        self._alerts[rank] = alert_vec
                self._version += 1
            self._counts["pushes"] += 1
            self._counts[mode] += 1
        _merge_hist().observe(time.perf_counter() - t0)
        return {"ok": True, "acked": acked, "mode": mode}

    # -- read paths ---------------------------------------------------------
    def legacy_view(self):
        """The pre-ISSUE-20 ``server._telemetry`` shape
        (``{gen: {rank: {"payload": {...}, "mono": t}}}``) — feeds
        :func:`_merge_view` so the detail scrape stays byte-compatible
        with the old merge path.  Each rank's families dict is
        shallow-copied UNDER its shard lock: apply_push mutates the
        stored dict in place, and a reader iterating the live dict
        (json.dumps / the fleet-RPC pickle) would race it.  Inner
        family dicts are replaced wholesale on upsert, never mutated,
        so the shallow copy is a consistent snapshot."""
        with self._meta:
            gens = dict(self._gens)
        out = {}
        for gen, shards in gens.items():
            ranks = {}
            for shard, lock in zip(shards, self._shard_locks):
                with lock:
                    for rank, e in shard.items():
                        ranks[rank] = {
                            "payload": {"time": e["time"],
                                        "families": dict(e["families"])},
                            "mono": e["mono"]}
            if ranks:
                out[gen] = ranks
        return out

    def snapshot_ages(self, generation, now_mono):
        """{rank: seconds since last push} for one generation —
        O(ranks) scalar reads, no family traffic."""
        shards = self._gen_shards(generation)
        ages = {}
        for shard, lock in zip(shards, self._shard_locks):
            with lock:
                for rank, e in shard.items():
                    if e["mono"] is not None:
                        ages[rank] = max(0.0, now_mono - e["mono"])
        return ages

    def summary(self, states, generation, num_workers, peer_timeout,
                now_mono, now_wall):
        """The O(families + anomalous ranks) scrape contract: peer
        counts + ONLY non-alive ranks + the incrementally-maintained
        family catalog and vectorized alert rollup, cached for
        ``ROLLUP_STALENESS_S``."""
        with self._agg_lock:
            cache = self._cache
            if cache is not None and cache[0] == self._version and \
                    now_mono - cache[1] < ROLLUP_STALENESS_S:
                out = dict(cache[2])
                out["time"] = now_wall
                return out
        ages = self.snapshot_ages(generation, now_mono)
        peers = {"alive": 0, "stale": 0, "lost": 0, "unknown": 0}
        anomalous = {}
        rank_states = {}
        age_max = None
        for rank in range(int(num_workers)):
            info = states.get(rank, {"state": "unknown", "age_s": None,
                                     "step": 0})
            snap_age = ages.get(rank)
            state = info["state"]
            if state == "alive" and (snap_age is None
                                     or snap_age > peer_timeout):
                state = "stale"
            peers[state] = peers.get(state, 0) + 1
            rank_states[str(rank)] = state
            if snap_age is not None:
                age_max = snap_age if age_max is None \
                    else max(age_max, snap_age)
            if state != "alive":
                anomalous[str(rank)] = {
                    "state": state, "age_s": info.get("age_s"),
                    "step": info.get("step", 0),
                    "snapshot_age_s": snap_age,
                    "generation": generation}
        with self._agg_lock:
            families = {f: dict(v)
                        for f, v in sorted(self._families.items())}
            vectors = {r: {"rules": dict(v["rules"]),
                           "firing": list(v["firing"])}
                       for r, v in self._alerts.items()}
            counts = dict(self._counts)
            version = self._version
        out = {"time": now_wall, "mode": "summary",
               "generation": generation, "world": int(num_workers),
               "peers": peers,
               "snapshot_age_max_s": age_max,
               "anomalous": anomalous,
               "families": families,
               "alerts": _rollup_from_vectors(vectors, rank_states),
               "push_stats": counts,
               "history": {"generations": len(
                   self.retained_generations()),
                   "dropped_generations": self.dropped_generations()}}
        with self._agg_lock:
            self._cache = (version, now_mono, out)
        return out


def _rollup_from_vectors(vectors, rank_states):
    """The vectorized :func:`alert_rollup`: renders the per-rank state
    vectors the store maintained at push time — O(alerting ranks), same
    output shape (``{"by_rank", "firing"}``)."""
    by_rank = {}
    firing = []
    for rank_str, vec in sorted((str(r), v) for r, v in vectors.items()):
        rank_state = rank_states.get(rank_str, "unknown")
        stale = rank_state != "alive"
        by_rank[rank_str] = {"rank_state": rank_state, "stale": stale,
                             "rules": dict(vec["rules"])}
        for rule in vec["firing"]:
            firing.append({"rank": rank_str, "rule": rule,
                           "stale": stale, "rank_state": rank_state})
    return {"by_rank": by_rank, "firing": firing}


# -- leader side --------------------------------------------------------------
def _merge_view(states, generation, num_workers, stored, peer_timeout,
                now_mono, now_wall):
    """The pre-ISSUE-20 merge algorithm, verbatim, over an explicit
    ``{gen: {rank: {"payload", "mono"}}}`` store — the detail
    (``?detail=rank``) scrape contract, byte-compat pinned by the fleet
    simulator at rank ≤ 8 against a shadow full-push store.

    State per rank (current generation):

    * ``alive`` — heartbeating within the peer timeout, snapshot fresh;
    * ``stale`` — alive but its last telemetry push is older than the
      peer timeout (the reporter wedged or was never armed);
    * ``lost``  — marked dead by the server (or silent past the
      timeout); its LAST pushed snapshot is retained and tagged;
    * ``unknown`` — never heartbeated this generation.

    Ranks from previous generations (a shrunk world) stay in the
    ``generations`` history tagged ``lost`` — a fleet consumer can see
    every retained generation's per-rank families, never a silent drop.
    """
    cur = stored.get(generation, {})
    ranks = {}
    for rank in range(num_workers):
        info = states.get(rank, {"state": "unknown", "age_s": None,
                                 "step": 0})
        entry = cur.get(rank)
        snap_age = (None if entry is None
                    else max(0.0, now_mono - entry["mono"]))
        state = info["state"]
        if state == "alive" and (snap_age is None
                                 or snap_age > peer_timeout):
            state = "stale"
        ranks[str(rank)] = {
            "state": state,
            "age_s": info.get("age_s"),
            "step": info.get("step", 0),
            "snapshot_age_s": snap_age,
            "generation": generation,
            "families": entry["payload"].get("families", {})
            if entry else {},
        }
    generations = {}
    for gen in sorted(stored):
        gen_ranks = {}
        for rank, entry in sorted(stored[gen].items()):
            if gen == generation:
                state = ranks[str(rank)]["state"]
            else:
                state = "lost"  # a rank of a dead generation
                # lost ranks keep their last snapshot in the CURRENT
                # view too when the world shrank past them
                if str(rank) not in ranks:
                    ranks[str(rank)] = {
                        "state": "lost", "age_s": None, "step": None,
                        "snapshot_age_s": max(
                            0.0, now_mono - entry["mono"]),
                        "generation": gen,
                        "families": entry["payload"].get("families", {}),
                    }
            gen_ranks[str(rank)] = {
                "state": state,
                "time": entry["payload"].get("time"),
                "families": entry["payload"].get("families", {}),
            }
        generations[str(gen)] = gen_ranks
    return {"time": now_wall, "generation": generation,
            "world": num_workers, "ranks": ranks,
            "generations": generations,
            "alerts": alert_rollup(ranks)}


def merge_server(server, detail=None, _now=None):
    """Join a control-plane :class:`KVServer`'s fleet store with its
    liveness layer into the fleet snapshot.

    ``detail``: ``None`` auto-selects (``"rank"`` at world ≤
    ``DETAIL_AUTO_RANKS``, else ``"summary"``); ``"rank"`` forces the
    full per-rank/per-generation view, anything else the summary.
    ``_now`` pins the wall-clock stamp (simulator/back-compat tests).
    """
    store = server.fleet_store()
    clock = getattr(server, "_clock", time.monotonic)
    now_mono = clock()
    peer_timeout = server._peer_timeout()
    states = server._peer_states()
    with server._lock:
        generation = getattr(server, "_generation", 0)
        num_workers = server.num_workers
    now_wall = time.time() if _now is None else _now
    if detail is None:
        detail = "rank" if num_workers <= DETAIL_AUTO_RANKS \
            else "summary"
    t0 = time.perf_counter()
    if detail in ("rank", "full", True):
        out = _merge_view(states, generation, num_workers,
                          store.legacy_view(), peer_timeout,
                          now_mono, now_wall)
        dropped = store.dropped_generations()
        if dropped:
            # absence-safe truncation marker: the key only appears once
            # MXNET_FLEET_HISTORY actually pruned (pre-ISSUE-20 readers
            # and the byte-compat pin never see it otherwise)
            out["history"] = {
                "retained_generations": len(
                    store.retained_generations()),
                "dropped_generations": dropped}
    else:
        out = store.summary(states, generation, num_workers,
                            peer_timeout, now_mono, now_wall)
    _rollup_hist().observe(time.perf_counter() - t0)
    return out


def alert_rollup(ranks):
    """Fleet-wide alert rollup from merged per-rank families: every
    rank's ``mxnet_alert_state`` one-hot gauges read back into
    {rule: state}, with non-``alive`` ranks' alerts tagged ``stale`` —
    a lost rank's last-known firing alert stays visible (never silently
    dropped), but a consumer can tell judgment from memory (ISSUE 13).
    The summary scrape uses the vectorized equivalent
    (:func:`_rollup_from_vectors`) instead of re-scanning families."""
    by_rank = {}
    firing = []
    for rank, v in sorted((ranks or {}).items()):
        fam = (v.get("families") or {}).get("mxnet_alert_state")
        if not fam:
            continue
        rank_state = v.get("state", "unknown")
        stale = rank_state != "alive"
        rules = {}
        for sample in fam.get("values", []):
            if sample.get("value") != 1:
                continue
            labels = sample.get("labels", {})
            rule, state = labels.get("rule"), labels.get("state")
            if not rule or not state:
                continue
            rules[rule] = state
            if state == "firing":
                firing.append({"rank": rank, "rule": rule,
                               "stale": stale,
                               "rank_state": rank_state})
        if rules:
            by_rank[rank] = {"rank_state": rank_state, "stale": stale,
                             "rules": rules}
    return {"by_rank": by_rank, "firing": firing}


def set_provider(fn):
    """Install the fleet-snapshot provider (the elastic launcher wires
    ``lambda detail=None: merge_server(server, detail=detail)``); None
    uninstalls.  Providers without a ``detail`` parameter still work
    (auto mode only)."""
    global _provider
    with _provider_lock:
        _provider = fn


def provider():
    with _provider_lock:
        return _provider


def _call_provider(fn, detail):
    if detail is not None:
        try:
            return fn(detail=detail)
        except TypeError:
            # a provider predating the detail contract: serve auto mode
            pass
    return fn()


def fleet_json(detail=None):
    """The ``/fleet.json`` payload: the provider's merged snapshot on
    the leader, a single-rank local view everywhere else (so the
    endpoint is meaningful on any process).  ``detail`` mirrors the
    ``?detail=`` query parameter (``rank`` | ``summary`` | None=auto).
    """
    fn = provider()
    if fn is not None:
        return _call_provider(fn, detail)
    import os
    rank = os.environ.get("MXNET_MULTIHOST_PROC_ID", "0")
    ranks = {str(rank): {"state": "alive", "age_s": 0.0,
                         "snapshot_age_s": 0.0,
                         "generation": None,
                         "families": local_payload()["families"]}}
    return {"time": time.time(), "generation": None, "world": 1,
            "ranks": ranks, "generations": {},
            "alerts": alert_rollup(ranks)}


# -- telemetry collector hooks ------------------------------------------------
def _collector_snapshot():
    """The ``fleet`` key of ``telemetry.snapshot()``: summary only (the
    full per-rank families live at /fleet.json; the snapshot stays
    readable)."""
    fn = provider()
    if fn is None:
        return {}
    snap = fn()
    if snap.get("mode") == "summary":
        return {"generation": snap.get("generation"),
                "world": snap.get("world"),
                "mode": "summary",
                "peers": snap.get("peers", {}),
                "anomalous": snap.get("anomalous", {}),
                "families": len(snap.get("families", {})),
                "push_stats": snap.get("push_stats", {}),
                "alerts": snap.get("alerts", {})}
    return {"generation": snap.get("generation"),
            "world": snap.get("world"),
            "ranks": {r: {"state": v.get("state"),
                          "age_s": v.get("age_s"),
                          "snapshot_age_s": v.get("snapshot_age_s"),
                          "families": len(v.get("families", {}))}
                      for r, v in snap.get("ranks", {}).items()},
            "alerts": snap.get("alerts",
                               alert_rollup(snap.get("ranks", {})))}


def _collector_samples():
    """Prometheus surface.  Detail worlds (≤ DETAIL_AUTO_RANKS):
    every rank's counter/gauge samples re-emitted with a ``rank``
    label (histogram sample families re-emit as counters — le labels
    survive the merge).  Summary worlds: fleet summary families only —
    re-emitting 1000 ranks × families into one text scrape is exactly
    the O(ranks × families) surface ISSUE 20 removes."""
    fn = provider()
    if fn is None:
        return []
    snap = fn()
    out = []
    if snap.get("mode") == "summary":
        peers = snap.get("peers", {})
        for state in ("alive", "stale", "lost", "unknown"):
            out.append(("mxnet_fleet_peers", "gauge",
                        "fleet ranks by merged liveness state",
                        {"state": state}, peers.get(state, 0)))
        age_max = snap.get("snapshot_age_max_s")
        if isinstance(age_max, (int, float)):
            out.append(("mxnet_fleet_snapshot_age_max_seconds", "gauge",
                        "oldest rank snapshot age in the fleet",
                        {}, age_max))
        for rank, v in sorted((snap.get("anomalous") or {}).items()):
            out.append(("mxnet_fleet_rank_state", "gauge",
                        "per-rank liveness in the fleet snapshot (1 = "
                        "the labelled state holds; summary mode emits "
                        "only non-alive ranks)",
                        {"rank": rank,
                         "state": v.get("state", "unknown")}, 1))
            if v.get("snapshot_age_s") is not None:
                out.append(("mxnet_fleet_snapshot_age_seconds", "gauge",
                            "age of each rank's last pushed registry "
                            "snapshot", {"rank": rank},
                            v["snapshot_age_s"]))
        return out
    state_counts = {}
    for rank, v in sorted(snap.get("ranks", {}).items()):
        state = v.get("state", "unknown")
        state_counts[state] = state_counts.get(state, 0) + 1
        out.append(("mxnet_fleet_rank_state", "gauge",
                    "per-rank liveness in the fleet snapshot (1 = the "
                    "labelled state holds)",
                    {"rank": rank, "state": state}, 1))
        if v.get("snapshot_age_s") is not None:
            out.append(("mxnet_fleet_snapshot_age_seconds", "gauge",
                        "age of each rank's last pushed registry "
                        "snapshot", {"rank": rank},
                        v["snapshot_age_s"]))
        for family, fam in sorted(v.get("families", {}).items()):
            mtype = fam.get("type", "gauge")
            if mtype == "histogram":
                mtype = "counter"  # flattened _bucket/_sum/_count rows
            for sample in fam.get("values", []):
                value = sample.get("value")
                if not isinstance(value, (int, float)) or \
                        isinstance(value, bool):
                    continue
                labels = dict(sample.get("labels", {}))
                labels["rank"] = rank
                out.append((family, mtype,
                            f"fleet-merged {family} (rank-labelled)",
                            labels, value))
    for state in ("alive", "stale", "lost", "unknown"):
        out.append(("mxnet_fleet_peers", "gauge",
                    "fleet ranks by merged liveness state",
                    {"state": state}, state_counts.get(state, 0)))
    return out
