"""Cross-rank telemetry aggregation: one fleet snapshot for N processes
(ISSUE 12 tentpole).

PR 11 made training multi-process, but each rank still kept its own
PR-5 registry — an operator (or the ROADMAP item-4 autoscaler) had to
scrape N exporters and join them by hand, and a dead rank's metrics
simply vanished.  This module closes that gap over the transport that
already exists:

* **rank side** — :class:`FleetReporter` (armed by the multi-host
  runtime when ``MXNET_FLEET_INTERVAL_S`` > 0) pushes the registry's
  flattened sample families (:meth:`MetricsRegistry.sample_families`)
  to the control-plane kvstore server on its OWN connection (a barrier
  blocking the main RPC socket must not stall telemetry), every
  interval and once more at shutdown/fault;
* **server side** — the :class:`~mxnet_tpu.kvstore_server.KVServer`
  stores the latest payload per ``(generation, rank)``;
* **leader side** — :func:`merge_server` joins payloads with the
  server's liveness layer into ONE fleet snapshot: per-rank families
  with ``state`` / ``age_s`` / staleness marks.  A dead rank keeps its
  last snapshot tagged ``state="lost"`` — never silently dropped — and
  every generation's history is retained, so "what was rank 1 doing
  when it died" reads off ``/fleet.json``.

Serving surfaces: the exporter's ``GET /fleet.json`` renders
:func:`fleet_json` (the registered provider on the leader, a local
single-rank view elsewhere), and the ``fleet`` telemetry collector
re-emits every rank's counter/gauge samples into the Prometheus dump
with a ``rank`` label plus ``mxnet_fleet_peers{state}`` /
``mxnet_fleet_snapshot_age_seconds{rank}`` summary families — the data
plane the ROADMAP item-4 autoscaler consumes.
"""
from __future__ import annotations

import logging
import threading
import time

log = logging.getLogger("mxnet_tpu.telemetry.fleet")

_provider_lock = threading.Lock()
_provider = None   # zero-arg callable -> fleet snapshot dict (the leader)


def _registry():
    from . import REGISTRY
    return REGISTRY


def local_payload():
    """This rank's pushable snapshot: flattened sample families plus a
    wall-clock stamp (all leaves JSON-native)."""
    return {"time": time.time(),
            "families": _registry().sample_families()}


# -- rank side ----------------------------------------------------------------
class FleetReporter:
    """Daemon thread pushing this rank's registry snapshot to the
    control-plane server every ``interval_s``; ``push_now()`` forces a
    final push on the fault/shutdown paths."""

    def __init__(self, host, port, rank, world, interval_s, timeout=10.0):
        self.rank = int(rank)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._client = None
        self._host, self._port = host, int(port)
        self._world = int(world)
        self._timeout = float(timeout)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="mx-fleet-reporter")
        self._thread.start()

    def _ensure_client(self):
        if self._client is None:
            from ..kvstore_server import KVClient
            self._client = KVClient(
                self._host, self._port, rank=self.rank,
                num_workers=self._world, timeout=self._timeout,
                heartbeat_interval=0)
        return self._client

    def _loop(self):
        # first push immediately: a rank killed early must still appear
        # in the fleet snapshot (lost, not vanished)
        while True:
            try:
                self.push_now()
            except Exception as e:  # noqa: BLE001 — telemetry push failures age the snapshot; they must not kill the reporter
                log.debug("fleet reporter push failed: %s", e)
                if self._stop.is_set():
                    return
            if self._stop.wait(self.interval_s):
                return

    def push_now(self):
        """One synchronous push (used by the loop and the fault path)."""
        client = self._ensure_client()
        client.push_telemetry(local_payload())

    def stop(self, final_push=True):
        self._stop.set()
        if final_push:
            try:
                self.push_now()
            except Exception as e:  # noqa: BLE001 — best-effort final sample on a possibly-dead transport
                log.debug("fleet reporter final push failed: %s", e)
        if self._client is not None:
            try:
                self._client.close()
            except Exception:  # graftlint: disable=swallowed-error -- best-effort teardown on a possibly-dead transport
                pass


# -- leader side --------------------------------------------------------------
def merge_server(server):
    """Join a control-plane :class:`KVServer`'s stored telemetry
    payloads with its liveness layer into the fleet snapshot.

    State per rank (current generation):

    * ``alive`` — heartbeating within the peer timeout, snapshot fresh;
    * ``stale`` — alive but its last telemetry push is older than the
      peer timeout (the reporter wedged or was never armed);
    * ``lost``  — marked dead by the server (or silent past the
      timeout); its LAST pushed snapshot is retained and tagged;
    * ``unknown`` — never heartbeated this generation.

    Ranks from previous generations (a shrunk world) stay in the
    ``generations`` history tagged ``lost`` — a fleet consumer can see
    every generation's per-rank families, never a silent drop.
    """
    now_mono = time.monotonic()
    peer_timeout = server._peer_timeout()
    states = server._peer_states()
    with server._lock:
        generation = getattr(server, "_generation", 0)
        num_workers = server.num_workers
        stored = {gen: dict(ranks)
                  for gen, ranks in server._telemetry.items()}
    cur = stored.get(generation, {})
    ranks = {}
    for rank in range(num_workers):
        info = states.get(rank, {"state": "unknown", "age_s": None,
                                 "step": 0})
        entry = cur.get(rank)
        snap_age = (None if entry is None
                    else max(0.0, now_mono - entry["mono"]))
        state = info["state"]
        if state == "alive" and (snap_age is None
                                 or snap_age > peer_timeout):
            state = "stale"
        ranks[str(rank)] = {
            "state": state,
            "age_s": info.get("age_s"),
            "step": info.get("step", 0),
            "snapshot_age_s": snap_age,
            "generation": generation,
            "families": entry["payload"].get("families", {})
            if entry else {},
        }
    generations = {}
    for gen in sorted(stored):
        gen_ranks = {}
        for rank, entry in sorted(stored[gen].items()):
            if gen == generation:
                state = ranks[str(rank)]["state"]
            else:
                state = "lost"  # a rank of a dead generation
                # lost ranks keep their last snapshot in the CURRENT
                # view too when the world shrank past them
                if str(rank) not in ranks:
                    ranks[str(rank)] = {
                        "state": "lost", "age_s": None, "step": None,
                        "snapshot_age_s": max(
                            0.0, now_mono - entry["mono"]),
                        "generation": gen,
                        "families": entry["payload"].get("families", {}),
                    }
            gen_ranks[str(rank)] = {
                "state": state,
                "time": entry["payload"].get("time"),
                "families": entry["payload"].get("families", {}),
            }
        generations[str(gen)] = gen_ranks
    return {"time": time.time(), "generation": generation,
            "world": num_workers, "ranks": ranks,
            "generations": generations,
            "alerts": alert_rollup(ranks)}


def alert_rollup(ranks):
    """Fleet-wide alert rollup from merged per-rank families: every
    rank's ``mxnet_alert_state`` one-hot gauges read back into
    {rule: state}, with non-``alive`` ranks' alerts tagged ``stale`` —
    a lost rank's last-known firing alert stays visible (never silently
    dropped), but a consumer can tell judgment from memory (ISSUE 13).
    """
    by_rank = {}
    firing = []
    for rank, v in sorted((ranks or {}).items()):
        fam = (v.get("families") or {}).get("mxnet_alert_state")
        if not fam:
            continue
        rank_state = v.get("state", "unknown")
        stale = rank_state != "alive"
        rules = {}
        for sample in fam.get("values", []):
            if sample.get("value") != 1:
                continue
            labels = sample.get("labels", {})
            rule, state = labels.get("rule"), labels.get("state")
            if not rule or not state:
                continue
            rules[rule] = state
            if state == "firing":
                firing.append({"rank": rank, "rule": rule,
                               "stale": stale,
                               "rank_state": rank_state})
        if rules:
            by_rank[rank] = {"rank_state": rank_state, "stale": stale,
                             "rules": rules}
    return {"by_rank": by_rank, "firing": firing}


def set_provider(fn):
    """Install the fleet-snapshot provider (the elastic launcher wires
    ``lambda: merge_server(server)``); None uninstalls."""
    global _provider
    with _provider_lock:
        _provider = fn


def provider():
    with _provider_lock:
        return _provider


def fleet_json():
    """The ``/fleet.json`` payload: the provider's merged snapshot on
    the leader, a single-rank local view everywhere else (so the
    endpoint is meaningful on any process)."""
    fn = provider()
    if fn is not None:
        return fn()
    import os
    rank = os.environ.get("MXNET_MULTIHOST_PROC_ID", "0")
    ranks = {str(rank): {"state": "alive", "age_s": 0.0,
                         "snapshot_age_s": 0.0,
                         "generation": None,
                         "families": local_payload()["families"]}}
    return {"time": time.time(), "generation": None, "world": 1,
            "ranks": ranks, "generations": {},
            "alerts": alert_rollup(ranks)}


# -- telemetry collector hooks ------------------------------------------------
def _collector_snapshot():
    """The ``fleet`` key of ``telemetry.snapshot()``: summary only (the
    full per-rank families live at /fleet.json; the snapshot stays
    readable)."""
    fn = provider()
    if fn is None:
        return {}
    snap = fn()
    return {"generation": snap.get("generation"),
            "world": snap.get("world"),
            "ranks": {r: {"state": v.get("state"),
                          "age_s": v.get("age_s"),
                          "snapshot_age_s": v.get("snapshot_age_s"),
                          "families": len(v.get("families", {}))}
                      for r, v in snap.get("ranks", {}).items()},
            "alerts": snap.get("alerts",
                               alert_rollup(snap.get("ranks", {})))}


def _collector_samples():
    """Prometheus surface: every rank's counter/gauge samples re-emitted
    with a ``rank`` label, plus fleet summary families.  Histogram
    sample families (``_bucket``/``_sum``/``_count``) re-emit as
    counters — le labels survive the merge."""
    fn = provider()
    if fn is None:
        return []
    snap = fn()
    out = []
    state_counts = {}
    for rank, v in sorted(snap.get("ranks", {}).items()):
        state = v.get("state", "unknown")
        state_counts[state] = state_counts.get(state, 0) + 1
        out.append(("mxnet_fleet_rank_state", "gauge",
                    "per-rank liveness in the fleet snapshot (1 = the "
                    "labelled state holds)",
                    {"rank": rank, "state": state}, 1))
        if v.get("snapshot_age_s") is not None:
            out.append(("mxnet_fleet_snapshot_age_seconds", "gauge",
                        "age of each rank's last pushed registry "
                        "snapshot", {"rank": rank},
                        v["snapshot_age_s"]))
        for family, fam in sorted(v.get("families", {}).items()):
            mtype = fam.get("type", "gauge")
            if mtype == "histogram":
                mtype = "counter"  # flattened _bucket/_sum/_count rows
            for sample in fam.get("values", []):
                value = sample.get("value")
                if not isinstance(value, (int, float)) or \
                        isinstance(value, bool):
                    continue
                labels = dict(sample.get("labels", {}))
                labels["rank"] = rank
                out.append((family, mtype,
                            f"fleet-merged {family} (rank-labelled)",
                            labels, value))
    for state in ("alive", "stale", "lost", "unknown"):
        out.append(("mxnet_fleet_peers", "gauge",
                    "fleet ranks by merged liveness state",
                    {"state": state}, state_counts.get(state, 0)))
    return out
