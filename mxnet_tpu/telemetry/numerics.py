"""Numerics observatory: in-trace training-health telemetry, non-finite
sentinels, and anomaly-triggered forensic dumps (ISSUE 14 tentpole).

PRs 12–13 made the *system* observable; nothing watched the *model*.  A
NaN produced mid-window silently corrupts the donated carry and
surfaces — if ever — as a garbage checkpoint hours later.  This module
closes that gap with the PyGraph lesson applied to health stats: the
instrumentation lives *inside* the already-captured window, so
observability costs zero extra dispatches.

* **In-trace stats** (:func:`trace_step`) — the fused / scanned /
  mesh-fused train steps fold a small per-step stat vector into their
  donated ``jit``/``shard_map`` program: global gradient L2 norm,
  parameter L2 norm, update ratio (‖Δw‖/‖w‖), a loss proxy (mean of the
  graph's primary output — the loss for MakeLoss/regression heads, the
  mean probability for SoftmaxOutput heads), and per-bucket non-finite
  element counts over the gradients (buckets = the same dtype-contiguous
  size-bounded parameter groups the collective planner uses, so a bad
  bucket names a *region* of the model).  The stats ride the window's
  existing outputs; the host reads them only at the window boundary —
  dispatches/step are unchanged and the update math is untouched, so
  weights stay bitwise identical to a numerics-off run.
* **Sentinel modes** (``MXNET_NUMERICS=off|warn|skip|halt``) — at the
  boundary a non-finite (or rule-breaching) window WARNs, SKIPs, or
  HALTs.  ``skip`` replays the MXNet dynamic loss-scaler idiom *inside
  the trace*: each step's update is gated on its own all-finite flag
  (``where(finite, new, old)``), so a poisoned step's update (params,
  optimizer state, aux, codec residuals) is dropped on device with no
  extra host sync, and training continues bit-identically to a manual
  skip.  ``halt`` raises a typed :class:`~mxnet_tpu.base.NonFiniteError`
  at the boundary.  An attached :class:`~mxnet_tpu.amp.LossScaler`
  consumes the same per-step flags (:func:`attach_loss_scaler`), so
  dynamic-scale backoff/growth needs no separate overflow sync.
* **Forensics** — a detected anomaly records a flight-ring event and
  dumps ``mxnet-numerics-<pid>-<n>.json``: the stats history, window /
  step numbers, per-bucket non-finite counts with parameter names, the
  RNG key path (counter), batch indices, and the last-good checkpoint
  step — "loss went NaN" starts from evidence, not archaeology.
* **Serving guard** — :func:`guard_rows` screens batch outputs so a
  model emitting non-finite logits fails *those requests* typed
  (``NonFiniteError``) instead of serving garbage
  (``MXNET_NUMERICS_SERVING``; ``mxnet_numerics_serving_nonfinite_total``).

Export: ``mxnet_numerics_*`` registry families (plain metrics — they
ride the PR-12 fleet push for per-rank visibility) plus a ``numerics``
collector in ``telemetry.snapshot()``.  The default alert pack gains
``nonfinite_window`` (page), ``grad_norm_explosion`` and ``loss_spike``
rate rules (telemetry/alerts.py).  The disabled path is one
module-global check (< 1 µs, the span/trace/failpoint bar).
"""
from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time

import numpy as np

from ..base import MXNetError, NonFiniteError

log = logging.getLogger("mxnet_tpu.telemetry.numerics")

MODES = ("off", "warn", "skip", "halt")

#: names of the core stat slots; per-bucket non-finite counts follow
STAT_NAMES = ("grad_norm", "param_norm", "update_ratio", "loss",
              "nonfinite")
N_CORE = len(STAT_NAMES)

# module-global fast gates: the ONLY thing a disabled caller touches
_mode = "off"
_serving_guard = True

_lock = threading.Lock()
_history = collections.deque(maxlen=512)
_windows = 0
_dumps = 0
_scalers = []          # attached LossScaler-likes (weak contract: small)
_counts = {"steps": 0, "nonfinite_steps": 0, "nonfinite_windows": 0,
           "rule_breach_windows": 0, "skipped_updates": 0}


# -- configuration ------------------------------------------------------------
def configure(mode=None, serving=None, history=None):
    """(Re)configure from the env knobs — called at telemetry import;
    tests flip modes directly."""
    global _mode, _serving_guard, _history
    from .. import config as _config
    if mode is None:
        mode = str(_config.get("MXNET_NUMERICS") or "off").strip().lower()
    if mode not in MODES:
        raise MXNetError(f"MXNET_NUMERICS={mode!r}: expected one of "
                         f"{MODES}")
    if serving is None:
        serving = bool(_config.get("MXNET_NUMERICS_SERVING"))
    if history is None:
        history = int(_config.get("MXNET_NUMERICS_HISTORY"))
    with _lock:
        if history != _history.maxlen:
            _history = collections.deque(_history, maxlen=max(16, history))
    _serving_guard = bool(serving)
    _mode = mode
    if mode != "off":
        _metrics()  # create the families eagerly: alert-rule rate
        # baselines need the counters present from the first armed tick
    return mode


def mode():
    return _mode


def armed():
    """True when the observatory watches train windows (mode != off) —
    the hot-path gate; one global read."""
    return _mode != "off"


def trace_mode():
    """The mode a train-step trace should bake in (part of its build
    signature: arming/disarming retraces, never silently drifts)."""
    return _mode


def serving_guard():
    """True when serving batch outputs are screened for non-finite rows
    — one global read (< 1 µs disabled bar)."""
    return _serving_guard


# -- registry families --------------------------------------------------------
def _metrics():
    from . import REGISTRY
    return {
        "grad_norm": REGISTRY.gauge(
            "mxnet_numerics_grad_norm",
            "global L2 norm of the last observed step's gradients "
            "(in-trace, read at window boundaries)"),
        "param_norm": REGISTRY.gauge(
            "mxnet_numerics_param_norm",
            "global L2 norm of the parameters after the last observed "
            "step's update"),
        "update_ratio": REGISTRY.gauge(
            "mxnet_numerics_update_ratio",
            "|param delta| / |params| of the last observed step (0 for "
            "a skipped update)"),
        "loss": REGISTRY.gauge(
            "mxnet_numerics_loss",
            "loss proxy of the last observed step: mean of the graph's "
            "primary output (the loss for MakeLoss/regression heads)"),
        "steps": REGISTRY.counter(
            "mxnet_numerics_steps_total",
            "train steps observed by the numerics observatory"),
        "nf_steps": REGISTRY.counter(
            "mxnet_numerics_nonfinite_steps_total",
            "observed train steps whose gradients/params/loss contained "
            "non-finite values"),
        "nf_windows": REGISTRY.counter(
            "mxnet_numerics_nonfinite_windows_total",
            "train windows containing at least one non-finite step (the "
            "nonfinite_window alert rule's family)"),
        "breaches": REGISTRY.counter(
            "mxnet_numerics_rule_breaches_total",
            "windows breaching a host-side numerics rule, by rule"),
        "skipped": REGISTRY.counter(
            "mxnet_numerics_skipped_updates_total",
            "poisoned per-step updates dropped on device by skip mode"),
        "nf_bucket": REGISTRY.counter(
            "mxnet_numerics_nonfinite_elements_total",
            "non-finite gradient elements observed, by parameter bucket"),
        "dumps": REGISTRY.counter(
            "mxnet_numerics_dumps_total",
            "forensic numerics dumps written"),
        "serving_nf": REGISTRY.counter(
            "mxnet_numerics_serving_nonfinite_total",
            "serving requests failed by the output-health guard "
            "(non-finite logits never served), by batcher"),
    }


# -- in-trace helpers (pure jax; callable only inside a trace) ---------------
def stat_groups(shapes, dtypes, names=None, bucket_mb=None):
    """Group parameters (training order) into dtype-contiguous,
    size-bounded stat buckets — the same grouping rule the collective
    planner uses (parallel/fused.plan_buckets), re-stated here so the
    telemetry layer never imports the parallel package.  Returns
    ``(groups, group_names)``: index lists plus a display name per
    group (joined member names, truncated)."""
    if bucket_mb is None:
        from .. import config as _config
        bucket_mb = float(_config.get("MXNET_COLLECTIVE_BUCKET_MB"))
    limit = max(1, int(float(bucket_mb) * (1 << 20)))
    groups, cur, cur_bytes, cur_dtype = [], [], 0, None
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        nb = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        if cur and (str(dtype) != cur_dtype or cur_bytes + nb > limit):
            groups.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
        cur_dtype = str(dtype)
    if cur:
        groups.append(cur)
    return groups, group_names(groups, names)


def group_names(groups, names=None):
    """Display name per group: joined member names, bounded length."""
    out = []
    for g in groups:
        if names is None:
            out.append(f"params[{g[0]}..{g[-1]}]")
            continue
        label = "+".join(names[i] for i in g)
        out.append(label if len(label) <= 80 else
                   f"{names[g[0]]}+..+{names[g[-1]]}")
    return out


def trace_step(mode_, grads, outs, old_params, new_params, gate_pairs,
               groups, axes=None):
    """One train step's in-trace numerics.  Returns ``(new_params,
    gated_trees, stats_vec)``:

    * ``stats_vec`` — float32 ``(N_CORE + len(groups),)``: grad norm,
      param norm (of the APPLIED params), update ratio, loss proxy,
      total non-finite count, then per-group non-finite gradient
      counts;
    * in ``skip`` mode every update is gated on the step's own
      all-finite flag: ``new_params`` and each ``(new, old)`` pair in
      ``gate_pairs`` (optimizer state, aux, codec residuals) select the
      OLD tree when the step is poisoned — the dynamic loss-scaler
      idiom, on device, no extra sync;
    * under ``shard_map`` pass ``axes`` so the loss proxy is the global
      batch mean (``pmean``); grads/params must already be
      replicated/reduced so every rank computes identical stats.

    All math is read-only over the step's existing values: a warn/halt
    trace leaves the update bit-for-bit what a numerics-off trace
    produces.
    """
    import jax
    import jax.numpy as jnp
    f32 = jnp.float32

    # one fused reduce per ARRAY, then batched scalar math over stacked
    # vectors: per-scalar add chains (and whole-tree concatenations,
    # which copy every buffer and break the donated carry's in-place
    # aliasing) both blow the <5% overhead gate on the CPU backend —
    # this shape keeps the thunk count ~4 per parameter
    g_sq = jnp.stack([jnp.sum(jnp.square(g.astype(f32)))
                      for g in grads])
    # a NaN/Inf element makes its array's sum of squares non-finite
    # (squares are non-negative: no cancellation can hide it), so the
    # per-array sentinel is FREE off the norm reductions — no second
    # elementwise pass over the gradients.  The unit is poisoned
    # ARRAYS: nf_groups[b] counts the parameters in bucket b whose
    # gradient went non-finite (an overflowing-but-finite sumsq reads
    # as poisoned too — conservative, never a miss).
    g_nf = (~jnp.isfinite(g_sq)).astype(f32)
    nf_groups = [jnp.sum(g_nf[grp[0]:grp[-1] + 1]) for grp in groups]
    if outs:
        loss = jnp.mean(outs[0].astype(f32))
        if axes is not None:
            loss = jax.lax.pmean(loss, axes)
        nf_loss = (~jnp.isfinite(loss)).astype(f32)
    else:
        loss = jnp.zeros((), f32)
        nf_loss = jnp.zeros((), f32)
    total_nf = jnp.sum(g_nf) + nf_loss
    finite = total_nf == 0

    if mode_ == "skip":
        new_params = tuple(
            jnp.where(finite, n, o)
            for n, o in zip(new_params, old_params))
        gated = [jax.tree_util.tree_map(
                     lambda n, o: jnp.where(finite, n, o), tn, to)
                 for tn, to in gate_pairs]
    else:
        gated = [tn for tn, _to in gate_pairs]

    grad_norm = jnp.sqrt(jnp.sum(g_sq))
    # per-step rows carry the gradient-side stats + the loss proxy;
    # the param-side stats (param_norm, update_ratio, final non-finite
    # param sentinel) are filled per WINDOW by window_param_stats — a
    # per-step pass over the params (let alone a new-old diff, which
    # keeps the pre-update tree live and costs a carry copy per step)
    # measured at 5-20% of step wall on CPU; window cadence amortizes
    # it by 1/K, and non-finite params always surface within the same
    # window anyway (a poisoned update makes the NEXT forward's loss
    # and gradients non-finite — propagation is the sentinel)
    stats = jnp.stack([grad_norm, jnp.zeros((), f32),
                       jnp.zeros((), f32), loss,
                       total_nf] + nf_groups)
    return new_params, gated, stats


def window_param_stats(stats, new_params, old_params):
    """Fill the window's LAST stat row with the param-side stats,
    computed once per dispatched window (outside the scan, inside the
    same jit — the one place reading the pre-window params costs a
    single carry copy instead of one per step): param L2 norm after the
    window, the window's cumulative update ratio ‖Δw‖/‖w_before‖ (0
    when every update was skipped), and the final-params non-finite
    sentinel folded into the row's non-finite count."""
    import jax.numpy as jnp
    f32 = jnp.float32
    n_sq = sum(jnp.sum(jnp.square(n.astype(f32))) for n in new_params)
    u_sq = sum(jnp.sum(jnp.square(n.astype(f32) - o.astype(f32)))
               for n, o in zip(new_params, old_params))
    o_sq = sum(jnp.sum(jnp.square(o.astype(f32)))
               for o in old_params)
    param_norm = jnp.sqrt(n_sq)
    ratio = jnp.sqrt(u_sq) / (jnp.sqrt(o_sq) + 1e-12)
    nf_params = (~jnp.isfinite(n_sq)).astype(f32)
    if stats.ndim == 1:
        return jnp.stack([stats[0], param_norm, ratio, stats[3],
                          stats[4] + nf_params, *stats[N_CORE:]])
    last = jnp.stack([stats[-1, 0], param_norm, ratio, stats[-1, 3],
                      stats[-1, 4] + nf_params, *stats[-1, N_CORE:]])
    return stats.at[-1].set(last)


def poison_armed():
    """True when the chaos ``train/poison_grad`` site is armed — baked
    into the train-step trace signature, so the in-trace poison
    multiply exists only in chaos runs: a production armed window pays
    zero extra gradient traffic for the injection hook."""
    from ..chaos.failpoints import arms
    return "train/poison_grad" in arms()


def poison_value():
    """Host-side chaos hook for the ``train/poison_grad`` site: returns
    the scalar every in-trace gradient is multiplied by — 1.0 normally
    (IEEE-exact identity, bitwise no-op), NaN/Inf when the failpoint
    fires for this window.  Arm ``train/poison_grad=raise`` for NaN or
    ``train/poison_grad=raise(inf)`` for Inf (docs/chaos.md)."""
    from ..chaos.failpoints import ChaosInjectedError, failpoint
    try:
        failpoint("train/poison_grad")
    except ChaosInjectedError as e:
        val = float("inf") if "'inf'" in str(e) else float("nan")
        log.warning("numerics: chaos poisoned this window's gradients "
                    "with %s", val)
        return np.float32(val)
    return np.float32(1.0)


# -- the fused overflow check (amp satellite) ---------------------------------
_finite_jit = None


def host_all_finite(arrays):
    """ONE fused device reduction + one host sync answering "is every
    array all-finite?" — the multi_all_finite idiom the dynamic loss
    scaler's overflow check shares with the in-window sentinel (the
    per-array ``isfinite().all()`` list the old check built is fused
    into a single jitted program, retraced only per shape set)."""
    import jax
    import jax.numpy as jnp
    global _finite_jit
    bufs = [getattr(a, "_data", a) for a in arrays if a is not None]
    if not bufs:
        return True
    if _finite_jit is None:
        def all_finite(xs):
            flags = [jnp.all(jnp.isfinite(x.astype(jnp.float32)))
                     for x in xs]
            return jnp.stack(flags).all()
        _finite_jit = jax.jit(all_finite)
    return bool(_finite_jit(tuple(bufs)))


def attach_loss_scaler(scaler):
    """Feed an amp ``LossScaler`` the per-step finite flags the
    boundary check observes: poisoned steps back the scale off, clean
    steps feed its growth window — no separate overflow sync."""
    with _lock:
        if scaler not in _scalers:
            _scalers.append(scaler)


def detach_loss_scaler(scaler):
    with _lock:
        if scaler in _scalers:
            _scalers.remove(scaler)


# -- host boundary check ------------------------------------------------------
def observe_window(stats, kind, first_step, window, group_labels=(),
                   nbatch=None):
    """Judge one dispatched window's stats at the host boundary.

    ``stats``: the window's in-trace stat rows — shape ``(n,)`` for a
    single fused step or ``(K, n)`` for a scanned window (the
    ``np.asarray`` here is the boundary's one tiny host read).  Updates
    the registry families + history ring; on a non-finite or
    rule-breaching window records a flight event, writes the forensic
    dump, feeds attached loss scalers, and — in ``halt`` mode — raises
    :class:`NonFiniteError`.  Returns the verdict dict (None when
    disarmed)."""
    if _mode == "off" or stats is None:
        return None
    if isinstance(stats, (tuple, list)) and not stats:
        return None
    from .. import config as _config
    arr = np.asarray(stats, np.float64)
    if arr.ndim == 1:
        arr = arr[None]
    K = arr.shape[0]
    gn_max = float(_config.get("MXNET_NUMERICS_GRAD_NORM_MAX"))
    m = _metrics()

    nf_col = arr[:, 4]
    core_bad = ~np.isfinite(arr[:, :N_CORE]).all(axis=1)
    nonfinite_steps = (nf_col > 0) | core_bad
    breach_steps = np.zeros(K, bool)
    if gn_max > 0:
        with np.errstate(invalid="ignore"):
            breach_steps = arr[:, 0] > gn_max
    n_nf = int(nonfinite_steps.sum())
    verdict = ("nonfinite" if n_nf else
               "rule_breach" if breach_steps.any() else "clean")

    last = arr[-1]
    m["grad_norm"].set(float(last[0]))
    m["param_norm"].set(float(last[1]))
    m["update_ratio"].set(float(last[2]))
    m["loss"].set(float(last[3]))
    m["steps"].inc(K)
    if n_nf:
        m["nf_steps"].inc(n_nf)
        m["nf_windows"].inc()
        if _mode == "skip":
            m["skipped"].inc(n_nf)
    if verdict == "rule_breach":
        m["breaches"].inc(labels={"rule": "grad_norm_max"})
    for g, label in enumerate(group_labels):
        col = N_CORE + g
        if col < arr.shape[1]:
            with np.errstate(invalid="ignore"):
                n = float(np.nan_to_num(arr[:, col],
                                        nan=0.0, posinf=0.0).sum())
            if n:
                m["nf_bucket"].inc(int(n), labels={"bucket": label})

    global _windows
    entries = []
    for j in range(K):
        entries.append({
            "step": int(first_step) + j, "kind": str(kind),
            "window": int(window),
            "grad_norm": float(arr[j, 0]), "param_norm": float(arr[j, 1]),
            "update_ratio": float(arr[j, 2]), "loss": float(arr[j, 3]),
            "nonfinite": float(arr[j, 4]),
        })
    with _lock:
        _windows += 1
        _history.extend(entries)
        _counts["steps"] += K
        _counts["nonfinite_steps"] += n_nf
        if n_nf:
            _counts["nonfinite_windows"] += 1
            if _mode == "skip":
                _counts["skipped_updates"] += n_nf
        if verdict == "rule_breach":
            _counts["rule_breach_windows"] += 1
        scalers = list(_scalers)
    for scaler in scalers:
        for j in range(K):
            scaler.update_scale(bool(nonfinite_steps[j]))

    result = {"verdict": verdict, "kind": str(kind),
              "window": int(window), "first_step": int(first_step),
              "steps": K, "nonfinite_steps": n_nf,
              "skipped": n_nf if (_mode == "skip" and n_nf) else 0}
    if verdict == "clean":
        return result

    bad = int(np.argmax(nonfinite_steps if n_nf else breach_steps))
    result.update({"bad_step": int(first_step) + bad,
                   "grad_norm": float(arr[bad, 0]),
                   "loss": float(arr[bad, 3])})
    from . import flight
    flight.record(
        "numerics",
        "nonfinite_window" if n_nf else "grad_norm_breach",
        severity="error", kind=kind, window=int(window),
        step=result["bad_step"], mode=_mode,
        grad_norm=float(arr[bad, 0]), loss=float(arr[bad, 3]),
        nonfinite=float(arr[bad, 4]),
        action=("skip" if _mode == "skip" else
                "halt" if _mode == "halt" else "warn"))
    dump_path = _dump_forensics(result, arr, entries, group_labels,
                                nbatch)
    result["dump"] = dump_path
    log.warning(
        "numerics: %s window %d (%s, step %d): grad_norm=%g loss=%g "
        "nonfinite=%g — %s%s", verdict, window, kind,
        result["bad_step"], arr[bad, 0], arr[bad, 3], arr[bad, 4],
        {"warn": "continuing (MXNET_NUMERICS=warn)",
         "skip": "poisoned update(s) dropped on device",
         "halt": "halting"}[_mode],
        f"; forensics: {dump_path}" if dump_path else "")
    if _mode == "halt":
        raise NonFiniteError(
            where=f"{kind} window {window}", step=result["bad_step"],
            stat="nonfinite" if n_nf else "grad_norm",
            value=float(arr[bad, 4] if n_nf else arr[bad, 0]),
            dump_path=dump_path,
            detail=f"grad_norm={arr[bad, 0]:g} loss={arr[bad, 3]:g}")
    return result


def _last_good_checkpoint_step():
    try:
        from . import _checkpoint_snapshot
        steps = [s.get("last_commit_step")
                 for s in _checkpoint_snapshot().values()
                 if isinstance(s.get("last_commit_step"), (int, float))]
        return int(max(steps)) if steps else None
    except Exception as e:  # noqa: BLE001 — forensics enrichment only
        log.debug("numerics: checkpoint step lookup failed: %s", e)
        return None


def _dump_dir():
    from .. import config as _config
    from . import flight
    return _config.get("MXNET_NUMERICS_DUMP_DIR") or flight.dump_dir()


def _dump_forensics(result, arr, window_entries, group_labels, nbatch):
    """Write ``mxnet-numerics-<pid>-<n>.json`` atomically; best-effort
    (the verdict — and a halt's raise — outrank the dump)."""
    global _dumps
    from . import flight
    from .. import random as _random
    with _lock:
        _dumps += 1
        n = _dumps
        history = list(_history)
    directory = _dump_dir()
    path = os.path.join(directory, f"mxnet-numerics-{os.getpid()}-{n}.json")
    nf_by_group = {}
    for g, label in enumerate(group_labels):
        col = N_CORE + g
        if col < arr.shape[1]:
            with np.errstate(invalid="ignore"):
                count = float(np.nan_to_num(arr[:, col], nan=0.0,
                                            posinf=0.0).sum())
            if count:
                nf_by_group[label] = count
    payload = {
        "pid": os.getpid(),
        "time": time.time(),
        "mode": _mode,
        "verdict": result["verdict"],
        "kind": result["kind"],
        "window": result["window"],
        "first_step": result["first_step"],
        "bad_step": result.get("bad_step"),
        "steps": result["steps"],
        "batch_index": nbatch,
        "rank": os.environ.get("MXNET_MULTIHOST_PROC_ID"),
        "rng_key_path": getattr(_random._state, "counter", None),
        "last_good_checkpoint_step": _last_good_checkpoint_step(),
        "nonfinite_by_bucket": nf_by_group,
        "window_stats": window_entries,
        "history": history,
    }
    try:
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _metrics()["dumps"].inc()
        flight.prune(directory, "mxnet-numerics-")
        return path
    except OSError as e:
        log.error("numerics: forensic dump failed: %s", e)
        return None


# -- serving output guard -----------------------------------------------------
def guard_rows(outputs, n_rows):
    """Row indices (set) of a serving batch whose float outputs contain
    non-finite values — the output-health guard's screen.  ``outputs``
    is the runner's list of batch-leading host arrays.  One vectorized
    ``isfinite`` pass per float output; empty set when the guard is
    off."""
    if not _serving_guard:
        return ()
    bad = None
    for out in outputs:
        a = np.asarray(out)
        if a.dtype.kind != "f" or a.shape[:1] != (n_rows,):
            continue
        ok = np.isfinite(a.reshape(n_rows, -1)).all(axis=1)
        bad = ~ok if bad is None else (bad | ~ok)
    if bad is None or not bad.any():
        return ()
    return set(np.nonzero(bad)[0].tolist())


def record_serving_nonfinite(batcher, n=1):
    """Account guard-failed requests + flight-ring the event."""
    _metrics()["serving_nf"].inc(int(n), labels={"batcher": str(batcher)})
    from . import flight
    flight.record("numerics", "serving_nonfinite", severity="error",
                  batcher=batcher, requests=int(n))


# -- read side ----------------------------------------------------------------
def history(last_n=None):
    """Recent per-step stat entries (oldest first)."""
    with _lock:
        entries = list(_history)
    return entries if last_n is None else entries[-int(last_n):]


def summary():
    """Aggregate counters + the grad-norm spread over the history ring
    (the soak harness's drift gate reads this)."""
    with _lock:
        counts = dict(_counts)
        windows = _windows
        gns = [e["grad_norm"] for e in _history
               if np.isfinite(e["grad_norm"])]
    out = {"mode": _mode, "windows": windows, **counts}
    if gns:
        out["grad_norm_last"] = gns[-1]
        out["grad_norm_max"] = float(max(gns))
        out["grad_norm_median"] = float(np.median(gns))
    return out


def monitor_summary(last_n=64):
    """``Monitor.toc()``-shaped rows ``[(step, stat_name, value_str)]``
    from the stats history — the fused-compatible alternative to
    ``Monitor(stat_func=...)`` (which opts the module out of the
    fused/scanned/mesh fast paths; see monitor.py)."""
    rows = []
    for entry in history(last_n):
        for stat in ("grad_norm", "param_norm", "update_ratio", "loss"):
            rows.append((entry["step"], stat, str(entry[stat])))
    return rows


def _collector_snapshot():
    snap = {"mode": _mode, "serving_guard": _serving_guard,
            "dumps": _dumps}
    snap.update(summary())
    return snap


def _reset_for_tests():
    """Disarm, clear history/counters, detach scalers."""
    global _mode, _windows, _dumps, _finite_jit
    with _lock:
        _history.clear()
        _scalers.clear()
        for k in _counts:
            _counts[k] = 0
        _windows = 0
        _dumps = 0
    _mode = "off"
    _finite_jit = None
