"""End-to-end tracing: one trace context per serving request / train
window, decomposable into stage spans (ISSUE 12 tentpole).

The PR-5 span tracer answers "how long does stage X take, in
aggregate"; it cannot answer "why was THIS request slow".  A trace
context is the per-unit-of-work answer: :func:`start` mints a
``trace_id`` and the context object rides the work itself — a serving
request carries it from ``ModelServer.predict_async`` through admission,
routing (surviving spill hops to sibling replicas), the batcher queue,
the stage/dispatch pipeline and the result fan-out; a scanned training
window carries it from batch collection through staging, the multi-host
rendezvous, the donated dispatch and the boundary metric flush.  Each
stage records an absolute ``(t0, t1)`` interval, so a finished trace
decomposes its end-to-end latency into named, tiling stages:

    serving: submit -> queue_wait -> stage -> staged_wait -> dispatch
             -> resolve        (+ events: admission verdict, route,
                                 spill hops, shed, timeout)
    train:   collect -> stage -> rendezvous -> dispatch
             -> boundary_flush

Stage exits reuse the span fan-out: every stage duration lands in the
``mxnet_trace_stage_seconds{kind,stage}`` histogram and the profiler's
chrome-trace stream (``cat="span"``); finished traces feed
``mxnet_trace_e2e_seconds{kind}`` plus the **exemplar store** —
``MXNET_TRACE_SAMPLE`` (default ``head=8,tail=64``) keeps the first
``head`` traces per kind and the ``tail`` slowest by e2e latency, so a
p99 outlier can be pulled from ``telemetry.snapshot()["trace"]`` and
read stage by stage.

Disabled (``MXNET_TRACE`` unset, the default) :func:`start` is one
module-global check returning the shared :data:`NULL_TRACE`, whose
methods are allocation-free no-ops — the same < 1 µs bar as a disabled
telemetry span / chaos failpoint (test-asserted, bench-tracked by
``trace_disabled_overhead_ns``).
"""
from __future__ import annotations

import itertools
import os
import threading
import time

from .. import profiler as _profiler

_enabled = False
_tls = threading.local()
_seq = itertools.count(1)

# filled in by telemetry/__init__ (shared histogram families)
_stage_hist = None
_e2e_hist = None


def enable():
    """Arm the trace context machinery for this process."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled():
    return _enabled


class _NullStage:
    """Shared no-op stage for the disabled path (nothing allocated)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_STAGE = _NullStage()


class _NullTrace:
    """Shared no-op trace: every call site records unconditionally and
    pays one attribute lookup + call when tracing is off."""

    __slots__ = ()
    trace_id = None
    kind = None
    t0 = 0.0

    def stage(self, name):
        return _NULL_STAGE

    def add_stage(self, name, t0, t1):
        pass

    def event(self, name, **fields):
        pass

    def finish(self, status="ok"):
        pass

    def finished(self):
        return True


NULL_TRACE = _NullTrace()


class _Stage:
    __slots__ = ("_trace", "_name", "_t0")

    def __init__(self, trace, name):
        self._trace = trace
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._trace.add_stage(self._name, self._t0, time.perf_counter())
        return False


class Trace:
    """One traced unit of work.  Thread-safe: a serving request's stages
    are recorded from the submit, stage and dispatch threads in turn."""

    __slots__ = ("trace_id", "kind", "name", "t0", "t_wall", "t_end",
                 "status", "stages", "events", "_lock")

    def __init__(self, kind, name=""):
        self.trace_id = f"{os.getpid():x}-{next(_seq):08d}"
        self.kind = str(kind)
        self.name = str(name)
        self.t0 = time.perf_counter()
        self.t_wall = time.time()
        self.t_end = None
        self.status = None
        self.stages = []   # (name, t0, t1) absolute perf_counter times
        self.events = []   # (t, name, fields)
        self._lock = threading.Lock()

    # -- recording -----------------------------------------------------------
    def stage(self, name):
        """Context manager recording one named stage interval."""
        return _Stage(self, name)

    def add_stage(self, name, t0, t1):
        """Record a stage from externally-measured endpoints (the queue
        wait is timed by whoever *claims* the request, not by a context
        manager the waiting thread could hold open)."""
        with self._lock:
            self.stages.append((name, float(t0), float(t1)))
        dur = max(0.0, t1 - t0)
        if _stage_hist is not None:
            _stage_hist.observe(dur, labels={"kind": self.kind,
                                             "stage": name})
        _profiler.record_op(f"trace/{self.kind}/{name}", dur * 1e6,
                            cat="span")

    def event(self, name, **fields):
        """Record a point event (admission verdict, spill hop, shed)."""
        with self._lock:
            self.events.append((time.perf_counter(), str(name),
                                {k: _native(v) for k, v in fields.items()}))

    def finish(self, status="ok"):
        """Close the trace (idempotent, first writer wins) and hand it
        to the exemplar store + e2e histogram."""
        with self._lock:
            if self.t_end is not None:
                return
            self.t_end = time.perf_counter()
            self.status = str(status)
        if _e2e_hist is not None:
            _e2e_hist.observe(self.e2e_s(), labels={"kind": self.kind})
        _EXEMPLARS.add(self)

    def finished(self):
        with self._lock:
            return self.t_end is not None

    # -- decomposition -------------------------------------------------------
    def e2e_s(self):
        with self._lock:
            end = self.t_end
        if end is None:
            end = time.perf_counter()
        return max(0.0, end - self.t0)

    def stage_total_s(self):
        with self._lock:
            return sum(max(0.0, t1 - t0) for _n, t0, t1 in self.stages)

    def coverage(self):
        """Fraction of the end-to-end latency the stage spans account
        for (>= 0.95 is the acceptance bar for a served request; small
        overlaps at hand-off points can push it past 1.0)."""
        e2e = self.e2e_s()
        return self.stage_total_s() / e2e if e2e > 0 else 1.0

    def to_dict(self):
        with self._lock:
            stages = [{"stage": n, "start_ms": round((t0 - self.t0) * 1e3, 4),
                       "dur_ms": round(max(0.0, t1 - t0) * 1e3, 4)}
                      for n, t0, t1 in self.stages]
            events = [{"t_ms": round((t - self.t0) * 1e3, 4),
                       "event": n, **f} for t, n, f in self.events]
            e2e = ((self.t_end - self.t0) * 1e3
                   if self.t_end is not None else None)
            status = self.status
        return {"trace_id": self.trace_id, "kind": self.kind,
                "name": self.name, "time": self.t_wall,
                "status": status,
                "e2e_ms": round(e2e, 4) if e2e is not None else None,
                "stage_total_ms": round(self.stage_total_s() * 1e3, 4),
                "coverage": round(self.coverage(), 4),
                "stages": stages, "events": events}


def _native(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    item = getattr(v, "item", None)
    if callable(item) and getattr(v, "ndim", 1) == 0:
        try:
            return item()
        except Exception:  # graftlint: disable=swallowed-error -- best-effort coercion; the str fallback below always works
            pass
    return str(v)


# -- exemplar store -----------------------------------------------------------
def _sample_policy():
    from .. import config as _config
    head, tail = 8, 64
    for part in str(_config.get("MXNET_TRACE_SAMPLE")).split(","):
        part = part.strip()
        if "=" not in part:
            continue
        k, v = part.split("=", 1)
        if k.strip() == "head":
            head = max(0, int(v))
        elif k.strip() == "tail":
            tail = max(0, int(v))
    return head, tail


class _ExemplarStore:
    """Head+tail sampling per trace kind: the first ``head`` traces
    (startup behaviour: cold compiles, first windows) plus the ``tail``
    slowest by e2e (the outliers a p99 decomposition needs)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._kinds = {}   # kind -> {"head": [], "slow": [(e2e, seq, dict)]}
        self._policy = None

    def add(self, trace):
        doc = trace.to_dict()
        e2e = doc["e2e_ms"] or 0.0
        with self._lock:
            if self._policy is None:
                self._policy = _sample_policy()
            head_n, tail_n = self._policy
            k = self._kinds.setdefault(
                trace.kind, {"count": 0, "head": [], "slow": [],
                             "last": None})
            k["count"] += 1
            k["last"] = doc
            if len(k["head"]) < head_n:
                k["head"].append(doc)
            elif tail_n:
                slow = k["slow"]
                slow.append((e2e, doc))
                if len(slow) > tail_n:
                    slow.sort(key=lambda t: t[0])
                    del slow[0: len(slow) - tail_n]

    def snapshot(self):
        with self._lock:
            out = {}
            for kind, k in sorted(self._kinds.items()):
                out[kind] = {
                    "count": k["count"],
                    "last": k["last"],
                    "head": list(k["head"]),
                    "slowest": [d for _e, d in
                                sorted(k["slow"], key=lambda t: -t[0])],
                }
            return out

    def reset(self):
        with self._lock:
            self._kinds.clear()
            self._policy = None


_EXEMPLARS = _ExemplarStore()


def exemplars():
    """{kind: {count, last, head[], slowest[]}} of finished traces —
    the payload behind ``telemetry.snapshot()["trace"]``."""
    return _EXEMPLARS.snapshot()


def reset_exemplars():
    _EXEMPLARS.reset()


# -- entry points -------------------------------------------------------------
def start(kind, name=""):
    """Mint a trace (or the shared no-op when tracing is disabled)."""
    if not _enabled:
        return NULL_TRACE
    return Trace(kind, name)


def current():
    """The thread's ambient trace (train windows propagate through the
    fit thread; serving traces ride the request object instead)."""
    tr = getattr(_tls, "trace", None)
    return tr if tr is not None else NULL_TRACE


def set_current(trace):
    """Install (or clear, with None) this thread's ambient trace."""
    _tls.trace = trace
