"""Prometheus scrape endpoint on the stdlib HTTP server.

``start_exporter(port)`` binds ``127.0.0.1:<port>`` (port 0 picks an
ephemeral one — used by tests/smoke) on a daemon thread and serves:

* ``GET /metrics``       — ``telemetry.prometheus_dump()`` (text 0.0.4)
* ``GET /snapshot.json`` — the full ``telemetry.snapshot()`` as JSON
* ``GET /fleet.json``    — the cross-rank fleet snapshot (the leader's
  merged per-rank registry view with liveness tags; a single-rank local
  view on processes without a fleet provider — see telemetry/fleet.py,
  ISSUE 12)
* ``GET /alerts.json``   — the in-process alert engine's full state:
  rule pack, lifecycle states, recent transitions, firing/pages lists
  (telemetry/alerts.py, ISSUE 13)
* ``GET /healthz``       — liveness an orchestrator can act on: 200
  ``ok`` normally; **503** naming the stalled section while a watchdog
  stall episode is active (an armed section fired and has not
  progressed since), after a chaos ``kill`` arm fired (the process
  is doomed/marked), or while a **page**-severity alert rule is firing
  (body names the firing rule; warn-severity alerts deliberately stay
  out of the readiness verdict) — so a wedged-but-running worker gets
  restarted instead of serving dead air (ISSUE 8 + 13 satellites).

Auto-start: importing :mod:`mxnet_tpu.telemetry` with
``MXNET_TELEMETRY_PORT`` set starts the endpoint; loopback-only by
design (front it with your own proxy if it must leave the host).
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("mxnet_tpu.telemetry")

_lock = threading.Lock()
_server = None
_thread = None


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-telemetry"

    def do_GET(self):  # noqa: N802 — http.server API
        from . import prometheus_dump, snapshot
        path, _, query = self.path.partition("?")
        if path in ("/metrics", "/metrics/"):
            body = prometheus_dump().encode("utf-8")
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path in ("/snapshot.json", "/snapshot"):
            body = json.dumps(snapshot(), default=str,
                              sort_keys=True).encode("utf-8")
            ctype = "application/json"
        elif path in ("/fleet.json", "/fleet"):
            from urllib.parse import parse_qs
            from . import fleet
            # ?detail=rank|full -> the full per-rank/per-generation
            # view; ?detail=summary -> the O(families + anomalous)
            # rollup; unset -> auto by world size
            # (docs/observability.md).  Anything else is a 400 — a typo
            # must not silently downgrade a small world to summary.
            raw = parse_qs(query, keep_blank_values=True).get(
                "detail", [""])[-1].strip().lower()
            if raw in ("rank", "full", "summary"):
                detail = raw
            elif raw == "":
                detail = None
            else:
                self.send_error(
                    400, "detail must be rank, full, or summary")
                return
            body = json.dumps(fleet.fleet_json(detail=detail),
                              default=str,
                              sort_keys=True).encode("utf-8")
            ctype = "application/json"
        elif path in ("/alerts.json", "/alerts"):
            from . import alerts
            body = json.dumps(alerts.alerts_json(), default=str,
                              sort_keys=True).encode("utf-8")
            ctype = "application/json"
        elif path == "/healthz":
            body, ctype, status = _health()
            self._reply(status, body, ctype)
            return
        else:
            self.send_error(404, "try /metrics, /snapshot.json, "
                                 "/fleet.json, /alerts.json, /healthz")
            return
        self._reply(200, body, ctype)

    def _reply(self, status, body, ctype):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):
        log.debug("exporter: " + fmt, *args)


def _health():
    """(body, content-type, status) for /healthz.  503 while a watchdog
    stall episode is active (body names the stalled section, so an
    orchestrator's restart log is a diagnosis), after a chaos ``kill``
    arm fired, or while a page-severity alert rule is firing (body
    names the rule — warn severity never flips readiness); 200
    otherwise."""
    from . import alerts, watchdog
    stalled = watchdog.stalled_sections()
    fatal = None
    try:
        from ..chaos.failpoints import fatal_site
        fatal = fatal_site()
    except Exception as e:  # noqa: BLE001 — liveness must not depend on chaos importing
        log.debug("healthz: chaos state unavailable: %s", e)
    if fatal is not None:
        return (f"fatal: chaos kill fired at {fatal}\n".encode("utf-8"),
                "text/plain", 503)
    if stalled:
        return (("stalled: " + ", ".join(stalled) + "\n").encode("utf-8"),
                "text/plain", 503)
    pages = alerts.firing_pages()
    if pages:
        return (("alert: " + ", ".join(pages) + "\n").encode("utf-8"),
                "text/plain", 503)
    return b"ok\n", "text/plain", 200


def start_exporter(port=None):
    """Start (or return the already-running) endpoint; -> bound port."""
    global _server, _thread
    if port is None:
        from .. import config as _config
        port = int(_config.get("MXNET_TELEMETRY_PORT"))
    with _lock:
        if _server is not None:
            return _server.server_address[1]
        server = ThreadingHTTPServer(("127.0.0.1", int(port)), _Handler)
        server.daemon_threads = True
        thread = threading.Thread(target=server.serve_forever,
                                  name="mx-telemetry-exporter", daemon=True)
        thread.start()
        _server, _thread = server, thread
        bound = server.server_address[1]
    log.info("telemetry exporter serving http://127.0.0.1:%d/metrics", bound)
    return bound


def exporter_port():
    """The running exporter's port (None when not running)."""
    with _lock:
        return _server.server_address[1] if _server is not None else None


def stop_exporter():
    global _server, _thread
    with _lock:
        server, _server = _server, None
        thread, _thread = _thread, None
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5)
