"""Process-wide metrics registry: counters, gauges, histograms.

One :class:`MetricsRegistry` instance (``telemetry.REGISTRY``) is the
single sink every subsystem reports into, replacing the four private
stores that grew organically (``serving/metrics.py`` reservoirs,
``CheckpointManager._stats``, profiler dispatch lanes, kvstore wire
counters).  Two feeding modes:

* **push** — hot paths create a metric once and update it
  (``REGISTRY.counter(name).inc()``); updates are a dict write under a
  lock, cheap enough for per-batch call sites.
* **pull** — subsystems that already keep their own thread-safe stats
  register a *collector* (a zero-arg callable returning a plain dict);
  ``snapshot()`` and ``prometheus_dump()`` invoke collectors at read
  time, so the subsystem pays nothing until someone actually looks.

``prometheus_dump()`` renders the standard text exposition format
(``# HELP`` / ``# TYPE`` + samples; histograms as cumulative
``_bucket{le=...}`` + ``_sum``/``_count``) so a stock Prometheus scrape
of the :mod:`exporter` endpoint works unmodified.
"""
from __future__ import annotations

import logging
import math
import threading

log = logging.getLogger("mxnet_tpu.telemetry")

_VALID_TYPES = ("counter", "gauge", "histogram")


def exponential_buckets(start=1e-4, factor=2.0, count=16):
    """Upper bounds ``start * factor**i`` — the default histogram grid
    (100 us .. ~3.3 s at the defaults, the span/step-lane range)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("exponential_buckets: start>0, factor>1, count>=1")
    return tuple(start * factor ** i for i in range(count))


def _label_key(labels):
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def to_native(obj):
    """Coerce numpy scalars/arrays (and other foreign leaves) to plain
    Python types, recursively.  Applied at the REGISTRY boundary — every
    collector snapshot and sample value passes through here — so
    ``json.dumps(telemetry.snapshot())`` round-trips without a custom
    encoder and the exporter's ``/snapshot.json`` never emits the
    ``repr`` of a numpy scalar (ISSUE 12 satellite)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k) if not isinstance(k, str) else k: to_native(v)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [to_native(v) for v in obj]
    item = getattr(obj, "item", None)
    if callable(item) and getattr(obj, "ndim", 0) == 0:
        try:
            return to_native(item())
        except Exception:  # graftlint: disable=swallowed-error -- best-effort coercion; the str fallback below always serializes
            pass
    tolist = getattr(obj, "tolist", None)
    if callable(tolist):
        try:
            return to_native(tolist())
        except Exception:  # graftlint: disable=swallowed-error -- best-effort coercion; the str fallback below always serializes
            pass
    return str(obj)


def _escape_label_value(value):
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _render_labels(key, extra=()):
    items = list(key) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + body + "}"


def _fmt(value):
    """Prometheus sample value: integral floats render as integers."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    f = float(value)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Metric:
    """Base: a named family holding one value-cell per label set."""

    kind = None

    def __init__(self, name, doc=""):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._cells = {}  # _label_key -> cell (kind-specific)

    def _cell(self, labels, factory):
        key = _label_key(labels)
        cell = self._cells.get(key)
        if cell is None:
            cell = self._cells.setdefault(key, factory())
        return key, cell


class Counter(_Metric):
    """Monotonic counter (per label set)."""

    kind = "counter"

    def inc(self, n=1, labels=None):
        if not isinstance(n, (int, float)):
            n = to_native(n)  # numpy scalars stay out of the cells
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            key = _label_key(labels)
            self._cells[key] = self._cells.get(key, 0) + n

    def value(self, labels=None):
        with self._lock:
            return self._cells.get(_label_key(labels), 0)

    def _samples(self):
        with self._lock:
            return [(self.name, key, v) for key, v in self._cells.items()]

    def _snapshot(self):
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._cells.items())]


class Gauge(_Metric):
    """Point-in-time value; ``set_fn`` installs a lazy read-time probe."""

    kind = "gauge"

    def __init__(self, name, doc=""):
        super().__init__(name, doc)
        self._fns = {}  # _label_key -> zero-arg callable

    def set(self, value, labels=None):
        with self._lock:
            self._cells[_label_key(labels)] = float(value)

    def inc(self, n=1, labels=None):
        with self._lock:
            key = _label_key(labels)
            self._cells[key] = self._cells.get(key, 0.0) + float(n)

    def dec(self, n=1, labels=None):
        self.inc(-n, labels)

    def set_fn(self, fn, labels=None):
        with self._lock:
            self._fns[_label_key(labels)] = fn

    def value(self, labels=None):
        key = _label_key(labels)
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                return self._cells.get(key, 0.0)
        try:
            return float(fn())
        except Exception as e:  # noqa: BLE001 — a dead probe reads as 0
            log.debug("gauge %s probe failed: %s", self.name, e)
            return 0.0

    def _keys(self):
        with self._lock:
            return sorted(set(self._cells) | set(self._fns))

    def _samples(self):
        return [(self.name, key, self.value(dict(key)))
                for key in self._keys()]

    def _snapshot(self):
        return [{"labels": dict(k), "value": self.value(dict(k))}
                for k in self._keys()]


class _HistCell:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Distribution over exponential (by default) bucket upper bounds."""

    kind = "histogram"

    def __init__(self, name, doc="", buckets=None):
        super().__init__(name, doc)
        bounds = tuple(sorted(buckets)) if buckets else exponential_buckets()
        if any(b <= a for a, b in zip(bounds, bounds[1:])):
            raise ValueError(f"histogram {self.name}: duplicate buckets")
        self.buckets = bounds

    def observe(self, value, labels=None):
        v = float(value)
        with self._lock:
            _key, cell = self._cell(
                labels, lambda: _HistCell(len(self.buckets)))
            for i, bound in enumerate(self.buckets):
                if v <= bound:
                    cell.counts[i] += 1
                    break
            cell.sum += v
            cell.count += 1

    def stats(self, labels=None):
        with self._lock:
            cell = self._cells.get(_label_key(labels))
            if cell is None:
                return {"count": 0, "sum": 0.0}
            return {"count": cell.count, "sum": cell.sum}

    def _samples(self):
        out = []
        with self._lock:
            for key, cell in self._cells.items():
                cum = 0
                for bound, n in zip(self.buckets, cell.counts):
                    cum += n
                    out.append((f"{self.name}_bucket", key, cum,
                                (("le", _fmt(bound)),)))
                out.append((f"{self.name}_bucket", key, cell.count,
                            (("le", "+Inf"),)))
                out.append((f"{self.name}_sum", key, cell.sum))
                out.append((f"{self.name}_count", key, cell.count))
        return out

    def _snapshot(self):
        with self._lock:
            out = []
            for key, cell in sorted(self._cells.items()):
                out.append({"labels": dict(key), "count": cell.count,
                            "sum": cell.sum,
                            "buckets": {_fmt(b): n for b, n in
                                        zip(self.buckets, cell.counts)}})
            return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Named metric families + named pull-collectors behind one snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}     # name -> _Metric
        self._collectors = {}  # name -> (snapshot_fn, samples_fn|None)

    # -- metric creation (get-or-create; kind collisions are an error) ------
    def _get_or_create(self, kind, name, doc, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = _KINDS[kind](name, doc, **kw)
            elif m.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {kind}")
            return m

    def counter(self, name, doc=""):
        return self._get_or_create("counter", name, doc)

    def gauge(self, name, doc=""):
        return self._get_or_create("gauge", name, doc)

    def histogram(self, name, doc="", buckets=None):
        return self._get_or_create("histogram", name, doc, buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    # -- collectors ---------------------------------------------------------
    def register_collector(self, name, snapshot_fn, samples_fn=None):
        """Register a pull source.  ``snapshot_fn()`` -> plain dict merged
        into ``snapshot()`` under ``name``; ``samples_fn()`` (optional) ->
        list of ``(family, type, help, labels_dict, value)`` tuples merged
        into the Prometheus dump.  Re-registering a name replaces it."""
        with self._lock:
            self._collectors[name] = (snapshot_fn, samples_fn)

    def unregister_collector(self, name):
        with self._lock:
            self._collectors.pop(name, None)

    def _collect(self):
        with self._lock:
            collectors = dict(self._collectors)
        out = {}
        for name, (snap_fn, _s) in collectors.items():
            try:
                # to_native at the boundary: a collector dict carrying
                # numpy scalars must not leak them into /snapshot.json
                out[name] = to_native(snap_fn())
            except Exception as e:  # noqa: BLE001 — one dead source must not poison the snapshot
                log.warning("telemetry collector %r failed: %s", name, e)
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # -- read side ----------------------------------------------------------
    def snapshot(self):
        """One dict with every local metric family plus every collector's
        raw snapshot (``serving``, ``checkpoint``, ``profiler``, …)."""
        with self._lock:
            metrics = dict(self._metrics)
        out = {"metrics": {
            name: {"type": m.kind, "doc": m.doc, "values": m._snapshot()}
            for name, m in sorted(metrics.items())}}
        out.update(self._collect())
        return out

    def sample_families(self):
        """Flattened numeric surface for cross-rank shipping: every
        local family AND every collector sample, as
        ``{family: {"type": t, "values": [{"labels": {...}, "value":
        v}]}}`` with native-typed (JSON-safe) leaves.  Histograms
        flatten into their ``_bucket`` / ``_sum`` / ``_count`` sample
        families, so a fleet merge re-labels samples mechanically."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
            collectors = dict(self._collectors)
        out = {}

        def _add(family, mtype, key, value, extra=()):
            fam = out.setdefault(family, {"type": mtype, "values": []})
            fam["values"].append({"labels": dict(list(key) + list(extra)),
                                  "value": to_native(value)})

        for m in metrics:
            for sample in m._samples():
                name, key, value = sample[0], sample[1], sample[2]
                extra = sample[3] if len(sample) > 3 else ()
                _add(name, m.kind, key, value, extra)
        for cname, (_snap, samples_fn) in sorted(collectors.items()):
            if samples_fn is None:
                continue
            try:
                samples = samples_fn()
            except Exception as e:  # noqa: BLE001 — one dead source must not poison the fleet push
                log.warning("telemetry samples for %r failed: %s", cname, e)
                continue
            for family, mtype, _help, labels, value in samples:
                _add(family, mtype, _label_key(labels), value)
        return out

    def prometheus_dump(self):
        """Prometheus text exposition (format version 0.0.4)."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
            collectors = dict(self._collectors)
        lines = []
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.doc or m.name}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for sample in m._samples():
                name, key, value = sample[0], sample[1], sample[2]
                extra = sample[3] if len(sample) > 3 else ()
                lines.append(
                    f"{name}{_render_labels(key, extra)} {_fmt(value)}")
        # collector samples, grouped so HELP/TYPE renders once per family
        families = {}
        for cname, (_snap, samples_fn) in sorted(collectors.items()):
            if samples_fn is None:
                continue
            try:
                samples = samples_fn()
            except Exception as e:  # noqa: BLE001 — one dead source must not poison the scrape
                log.warning("telemetry samples for %r failed: %s", cname, e)
                continue
            for family, mtype, help_, labels, value in samples:
                if mtype not in _VALID_TYPES:
                    mtype = "gauge"
                fam = families.setdefault(family, (mtype, help_, []))
                fam[2].append((_label_key(labels), value))
        for family in sorted(families):
            mtype, help_, samples = families[family]
            lines.append(f"# HELP {family} {help_ or family}")
            lines.append(f"# TYPE {family} {mtype}")
            for key, value in samples:
                lines.append(f"{family}{_render_labels(key)} {_fmt(value)}")
        return "\n".join(lines) + "\n"


# -- delta sampling (ISSUE 20) ------------------------------------------------
class SampleDeltaEncoder:
    """Delta-encode successive :meth:`MetricsRegistry.sample_families`
    snapshots against the last snapshot the receiver ACKNOWLEDGED, so an
    unchanged family costs ~0 wire bytes and ~0 merge work on the fleet
    leader (ISSUE 20 tentpole).

    Protocol (one encoder per pushing rank, one decoder per rank on the
    leader's :class:`~mxnet_tpu.telemetry.fleet.FleetStore`):

    * ``encode(payload)`` assigns a monotonically increasing ``seq`` and
      returns either a **full** payload (``{"seq", "time", "families"}``
      — always on the first push or after :meth:`reset`) or a **delta**
      payload ``{"time", "delta": {"base", "seq", "changed", "removed"}}``
      where ``base`` names the acked snapshot the delta applies to;
    * the receiver replies ``{"acked": seq}`` when it applied the push,
      or ``{"resync": True}`` when its baseline for this rank does not
      match ``base`` (server restart, lost ack, generation bump) — the
      caller then calls :meth:`reset` and sends exactly ONE full push;
    * ``ack(seq)`` commits the pending snapshot as the new baseline.
      A push whose ack is lost leaves the baseline untouched, so the
      next delta still applies cleanly against what the server last
      confirmed — or triggers the resync path, never silent skew.
    """

    def __init__(self):
        self._seq = 0
        self._acked_seq = None
        self._acked = None       # family dict the receiver confirmed
        self._pending = {}       # seq -> families awaiting ack

    def encode(self, payload):
        families = payload.get("families") or {}
        self._seq += 1
        seq = self._seq
        # supersede older unacked snapshots: pushes are synchronous, a
        # lost one is replaced by the next (the baseline never advances
        # past an ack, so correctness does not depend on them)
        self._pending = {seq: families}
        if self._acked is None:
            out = dict(payload)
            out["seq"] = seq
            return out
        base = self._acked
        changed = {f: fam for f, fam in families.items()
                   if base.get(f) != fam}
        removed = [f for f in base if f not in families]
        out = {k: v for k, v in payload.items() if k != "families"}
        out["delta"] = {"base": self._acked_seq, "seq": seq,
                        "changed": changed, "removed": removed}
        return out

    def ack(self, seq):
        families = self._pending.pop(seq, None)
        if families is not None:
            self._acked = families
            self._acked_seq = seq

    def reset(self):
        """Forget the baseline: the next :meth:`encode` emits a full
        snapshot (the resync path when the receiver forgot this rank)."""
        self._acked = None
        self._acked_seq = None
        self._pending = {}
