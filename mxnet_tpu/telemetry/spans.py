"""Span tracer: ``with telemetry.span("fit/step/h2d"): ...``.

Thread-safe, nestable, and ~zero-cost when telemetry is disabled: the
disabled path is one module-global check and a shared no-op context
manager (no allocation, well under a microsecond — asserted by
``tests/test_telemetry.py``).

An enabled span, on exit, fans its duration out to every sink at once:

* the profiler's chrome-trace stream (``profiler.record_op`` with
  ``cat="span"``) — spans land in the same ``profiler.dump()`` JSON and
  ``profiler.dumps()`` aggregate table as op dispatches, on the thread's
  own lane, so nesting renders natively in chrome://tracing;
* a ``jax.profiler.TraceAnnotation`` when a jax xplane trace is active
  (``MXNET_PROFILER_XPLANE_DIR``), so spans also show up in
  TensorBoard/perfetto next to the XLA device timeline;
* the ``mxnet_span_seconds`` histogram in the global registry
  (label ``span=<name>``), which is what ``snapshot()`` /
  ``prometheus_dump()`` expose.

Naming convention (docs/observability.md): slash-separated paths,
``<subsystem>/<operation>[/<phase>]`` — e.g. ``fit/step/h2d_stage``,
``serving/batch/run``, ``ckpt/save/snapshot``.
"""
from __future__ import annotations

import threading
import time

from .. import profiler as _profiler

_enabled = False
_tls = threading.local()

# filled in by telemetry/__init__ (one histogram family for all spans)
_span_hist = None


def enable():
    """Turn the span tracer + step-time breakdown on for this process."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled():
    return _enabled


def _stack():
    s = getattr(_tls, "spans", None)
    if s is None:
        s = _tls.spans = []
    return s


def current_span():
    """Name of the innermost open span on this thread (None outside)."""
    s = getattr(_tls, "spans", None)
    return s[-1] if s else None


def span_stack():
    """Open span names on this thread, outermost first."""
    return tuple(getattr(_tls, "spans", ()) or ())


class _NullSpan:
    """Shared no-op for the disabled path — nothing allocated per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("name", "_t0", "_jax")

    def __init__(self, name):
        self.name = name
        self._t0 = None
        self._jax = None

    def __enter__(self):
        _stack().append(self.name)
        if _profiler.jax_trace_dir():
            try:
                import jax
                self._jax = jax.profiler.TraceAnnotation(self.name)
                self._jax.__enter__()
            except Exception:  # graftlint: disable=swallowed-error -- xplane annotation is garnish; the span itself must never fail
                self._jax = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur_s = time.perf_counter() - self._t0
        if self._jax is not None:
            self._jax.__exit__(*exc)
        s = _stack()
        if s and s[-1] == self.name:
            s.pop()
        if _span_hist is not None:
            _span_hist.observe(dur_s, labels={"span": self.name})
        _profiler.record_op(self.name, dur_s * 1e6, cat="span")
        return False


def span(name):
    """Context manager timing one named region (no-op while disabled)."""
    if not _enabled:
        return _NULL_SPAN
    return _Span(name)
