"""Hang watchdog: dump every thread's stack when progress stops.

A wedged fused step, a serving runner stuck in a compile, a checkpoint
writer deadlocked on a lock — all present identically to an operator: a
silent process.  The watchdog turns that silence into a diagnosis:

* sections where progress is *expected* wrap themselves in
  ``watchdog.arm(name)`` (the fit loop arms ``train/fit`` for the whole
  run and ``beat``\\ s every batch; a batcher worker arms
  ``serving/<name>`` around each batch it executes);
* a daemon heartbeat checker wakes a few times per armed timeout; an
  armed section whose last beat is older than ``MXNET_WATCHDOG_S``
  seconds *fires*: all-thread stacks (``sys._current_frames``) plus the
  live ``telemetry.snapshot()`` go to stderr AND a dump file
  (``mxnet-watchdog-<pid>-<n>.txt`` in ``MXNET_WATCHDOG_DIR`` or cwd);
* one dump per stall episode — it re-arms only after progress resumes.

``MXNET_WATCHDOG_S=0`` (the default) disables everything: ``arm`` hands
back a shared no-op context and no thread is ever spawned.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import traceback

log = logging.getLogger("mxnet_tpu.telemetry.watchdog")

_lock = threading.Lock()
_entries = {}   # name -> {"armed", "count", "last", "timeout", "fired_count"}
_state = {"thread": None, "stop": None, "fires": 0, "last_dump": None}


def _timeout_s():
    from .. import config as _config
    return float(_config.get("MXNET_WATCHDOG_S"))


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class _Armed:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        with _lock:
            e = _entries.setdefault(self.name, {
                "armed": 0, "count": 0, "last": time.monotonic(),
                "timeout": 0.0, "scale": 1.0, "fired_count": None})
            e["armed"] += 1
            e["timeout"] = _timeout_s()
            e["count"] += 1
            e["last"] = time.monotonic()
        _ensure_thread()
        return self

    def __exit__(self, *exc):
        with _lock:
            e = _entries.get(self.name)
            if e is not None:
                e["armed"] = max(0, e["armed"] - 1)
                e["last"] = time.monotonic()
        return False


def active():
    """True when the watchdog knob is set (arm() is not a no-op)."""
    return _timeout_s() > 0


def arm(name):
    """Context manager marking a region where progress is expected;
    pair with :func:`beat` for long-running loops."""
    if not active():
        return _NULL_CTX
    return _Armed(name)


def beat(name):
    """Record progress for an armed section (cheap; no-op when the
    section was never armed)."""
    with _lock:
        e = _entries.get(name)
        if e is not None:
            e["count"] += 1
            e["last"] = time.monotonic()


def set_scale(name, factor):
    """Scale an armed section's stall deadline.  A K-step scanned fit
    window beats once per WINDOW, not per batch, so a healthy K=32 run
    legitimately goes ~32 batch-times between beats — the fit loop sets
    the scale to the window size (and back to 1) so MXNET_WATCHDOG_S
    keeps meaning \"per expected progress unit\" without retuning."""
    with _lock:
        e = _entries.get(name)
        if e is not None:
            e["scale"] = max(1.0, float(factor))


def fires():
    """How many times the watchdog has fired in this process."""
    with _lock:
        return _state["fires"]


def stalled_sections():
    """Armed sections currently in a stall episode: the watchdog fired
    for them and no progress (beat / re-arm / exit) has happened since.
    The episode ends the moment the section beats or exits — this is
    what ``/healthz`` keys its 503 on (docs/observability.md)."""
    with _lock:
        return sorted(
            name for name, e in _entries.items()
            if e["armed"] > 0 and e["fired_count"] is not None
            and e["fired_count"] == e["count"])


def last_dump():
    """Path of the most recent dump file (None before any fire)."""
    with _lock:
        return _state["last_dump"]


def _ensure_thread():
    with _lock:
        if _state["thread"] is not None and _state["thread"].is_alive():
            return
        _state["stop"] = threading.Event()
        t = threading.Thread(target=_loop, name="mx-telemetry-watchdog",
                             daemon=True)
        _state["thread"] = t
        t.start()


def _stop_for_tests():
    with _lock:
        stop, _state["thread"] = _state["stop"], None
        _entries.clear()
    if stop is not None:
        stop.set()


def _loop():
    while True:
        with _lock:
            stop = _state["stop"]
            timeouts = [e["timeout"] for e in _entries.values()
                        if e["armed"] > 0 and e["timeout"] > 0]
        interval = max(0.02, min(timeouts) / 4) if timeouts else 0.5
        if stop is None or stop.wait(interval):
            return
        _check()


def _check():
    now = time.monotonic()
    stale = []
    with _lock:
        for name, e in _entries.items():
            if e["armed"] <= 0 or e["timeout"] <= 0:
                continue
            if e["fired_count"] == e["count"]:
                continue  # already dumped this stall episode
            age = now - e["last"]
            if age > e["timeout"] * e.get("scale", 1.0):
                e["fired_count"] = e["count"]
                stale.append((name, age))
    for name, age in stale:
        _fire(name, age)


def _render_dump(name, age):
    lines = [f"== mxnet_tpu watchdog: no progress on {name!r} for "
             f"{age:.1f}s (pid {os.getpid()}) =="]
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sorted(sys._current_frames().items()):
        lines.append(f"-- thread {names.get(ident, '?')} (ident {ident}) --")
        lines.extend(ln.rstrip("\n")
                     for ln in traceback.format_stack(frame))
    lines.append("-- telemetry snapshot --")
    try:
        from . import snapshot
        lines.append(json.dumps(snapshot(), indent=1, default=str,
                                sort_keys=True))
    except Exception as e:  # noqa: BLE001 — the stack dump must land even if a collector wedged too
        lines.append(f"(snapshot unavailable: {type(e).__name__}: {e})")
    return "\n".join(lines) + "\n"


def _fire(name, age):
    text = _render_dump(name, age)
    sys.stderr.write(text)
    sys.stderr.flush()
    from . import flight as _flight
    from .. import config as _config
    _flight.record("watchdog", "fire", severity="error", section=name,
                   age_s=round(age, 3))
    directory = _config.get("MXNET_WATCHDOG_DIR") or os.getcwd()
    with _lock:
        n = _state["fires"] + 1
    path = os.path.join(directory,
                        f"mxnet-watchdog-{os.getpid()}-{n}.txt")
    try:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, path)
        # fires and last_dump flip together, AFTER the dump landed: a
        # poller that sees the new fire count can read the dump path —
        # rendering the (large) snapshot must not widen that window
        with _lock:
            _state["fires"] += 1
            _state["last_dump"] = path
        # dump retention (MXNET_WATCHDOG_KEEP): stall episodes must not
        # grow the dump directory without bound
        _flight.prune(directory, "mxnet-watchdog-")
        log.error("watchdog: %r stalled %.1fs — dump written to %s",
                  name, age, path)
    except OSError as e:
        with _lock:
            _state["fires"] += 1
        log.error("watchdog: %r stalled %.1fs — dump file failed (%s); "
                  "stacks were written to stderr", name, age, e)
    # the stall IS a fatal-adjacent event: land the flight ring next to
    # the stack dump so the postmortem has the decision history too
    _flight.auto_dump(f"watchdog:{name}")
