"""In-process SLO alert engine (ISSUE 13 tentpole, half two).

PR 12 ended with an alarm *table in the docs* — a human had to read the
scrape and decide.  This module turns that table into machine-readable
judgments evaluated inside the process: declarative rules over registry
samples, a pending → firing → resolved lifecycle with for-duration
hysteresis and per-rule cooldown, and a default rule pack codifying the
documented alarms (watchdog stall, corrupt checkpoint, spill storm,
shed burn rate, retrace ratchet, RSS leak slope, fleet snapshot
staleness).  Every transition lands in the flight ring and the
``mxnet_alert_*`` families; firing **page**-severity alerts flip
``/healthz`` to 503 and the new ``GET /alerts.json`` exporter route
serves the full state — the judgment layer the ROADMAP item-4
autoscaler actuates against, and the signal the chaos soak harness
(``python -m mxnet_tpu.chaos.soak``) gates CI on.

Rule kinds:

* **threshold** — reduced family value compared against a bound
  (``mxnet_watchdog_stalled_sections > 0``);
* **rate** — change per second over a lookback window
  (``mxnet_serving_router_spill_total`` rising faster than N/s);
* **absence** — a family that should always have samples has none
  (a reporter that should be pushing went silent);
* **burn_rate** — multi-window SLO burn: the bad/total ratio over a
  *fast* and a *slow* window must BOTH exceed ``factor`` × the error
  budget (``objective``) — the standard two-window burn-rate alarm, so
  a single shed blip neither pages (fast-only) nor does a slow leak
  hide (slow-only).  docs/observability.md has the math.

Lifecycle: a true condition moves a rule to ``pending``; held for
``for_s`` seconds it escalates to ``firing``; a false condition from
``firing`` moves to ``resolved``, which decays to ``inactive`` after
``cooldown_s`` — and re-firing is suppressed until the cooldown
expires, so a flapping signal cannot page in a loop.

Rank-local engines export their state as registry gauges
(``mxnet_alert_state{rule,state}``), which ride the PR-12 fleet push —
the leader's ``/fleet.json`` carries a fleet-wide alert rollup with
lost ranks' stale alerts tagged.

``MXNET_ALERTS=<seconds>`` arms a daemon evaluation thread at that
interval; the disabled module-level :func:`tick` is one global check
(< 1 µs, bench-gated like span/trace/failpoint).
"""
from __future__ import annotations

import collections
import logging
import sys
import threading
import time

from ..base import MXNetError

log = logging.getLogger("mxnet_tpu.telemetry.alerts")

SEVERITIES = ("warn", "page")
KINDS = ("threshold", "rate", "absence", "burn_rate")
STATES = ("inactive", "pending", "firing", "resolved")

# module-global fast gate: the ONLY thing a disabled tick() touches
_armed = False

_lock = threading.Lock()
_engine = None
_thread = None
_stop = None


class AlertRule:
    """One declarative rule over registry samples."""

    def __init__(self, name, family, kind="threshold", op=">", value=0.0,
                 for_s=0.0, cooldown_s=30.0, severity="warn",
                 reduce="sum", labels=None, window_s=60.0,
                 total_family=None, objective=0.05, factor=2.0,
                 fast_s=60.0, slow_s=300.0, doc=""):
        if kind not in KINDS:
            raise MXNetError(f"alert rule {name!r}: unknown kind {kind!r}; "
                             f"expected one of {KINDS}")
        if severity not in SEVERITIES:
            raise MXNetError(f"alert rule {name!r}: unknown severity "
                             f"{severity!r}; expected one of {SEVERITIES}")
        if op not in (">", "<"):
            raise MXNetError(f"alert rule {name!r}: op must be > or <")
        if reduce not in ("sum", "max", "min"):
            raise MXNetError(f"alert rule {name!r}: reduce must be "
                             "sum/max/min")
        if kind == "burn_rate" and not total_family:
            raise MXNetError(f"alert rule {name!r}: burn_rate needs "
                             "total_family")
        self.name = str(name)
        self.family = str(family)
        self.kind = kind
        self.op = op
        self.value = float(value)
        self.for_s = float(for_s)
        self.cooldown_s = float(cooldown_s)
        self.severity = severity
        self.reduce = reduce
        self.labels = dict(labels or {})
        self.window_s = float(window_s)
        self.total_family = total_family
        self.objective = float(objective)
        self.factor = float(factor)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.doc = doc

    def families(self):
        fams = {self.family}
        if self.total_family:
            fams.add(self.total_family)
        return fams

    def _match(self, rows):
        return [v for labels, v in rows
                if all(labels.get(k) == v2
                       for k, v2 in self.labels.items())]

    def _reduce(self, rows):
        vals = self._match(rows)
        if not vals:
            # a family the registry KNOWS but with no matching cells is
            # a zero counter under sum-reduction; max/min genuinely
            # have no data
            return 0.0 if self.reduce == "sum" else None
        if self.reduce == "max":
            return max(vals)
        if self.reduce == "min":
            return min(vals)
        return sum(vals)

    def _compare(self, v):
        return v > self.value if self.op == ">" else v < self.value

    def _windowed_delta(self, history, now, window):
        """(delta_value, delta_t) against the oldest point within
        ``window`` seconds (monotone counters assumed)."""
        anchor = None
        for t, v in history:
            if now - t <= window:
                anchor = (t, v)
                break
        if anchor is None or not history:
            return None
        t1, v1 = history[-1]
        dt = t1 - anchor[0]
        if dt <= 0:
            return None
        return v1 - anchor[1], dt

    def evaluate(self, samples, history, now):
        """-> (measured_value, condition_bool).  ``samples`` is
        {family: [(labels, value)]}; ``history`` is this rule's engine-
        kept deque (appended by the engine AFTER evaluation)."""
        rows = samples.get(self.family)
        if self.kind == "absence":
            present = bool(self._match(rows or []))
            return (1.0 if present else 0.0), not present
        if self.kind == "threshold":
            v = self._reduce(rows or [])
            if v is None:
                return None, False
            return v, self._compare(v)
        if self.kind == "rate":
            d = self._windowed_delta(history, now, self.window_s)
            if d is None:
                return None, False
            rate = d[0] / d[1]
            return rate, self._compare(rate)
        # burn_rate: history entries are (t, (bad, total))
        def burn(window):
            anchor = None
            for t, (b, tot) in history:
                if now - t <= window:
                    anchor = (b, tot)
                    break
            if anchor is None or not history:
                return None
            b1, tot1 = history[-1][1]
            d_bad, d_total = b1 - anchor[0], tot1 - anchor[1]
            if d_total <= 0:
                return 0.0
            return (d_bad / d_total) / max(1e-12, self.objective)
        fast, slow = burn(self.fast_s), burn(self.slow_s)
        if fast is None or slow is None:
            return None, False
        return fast, (fast >= self.factor and slow >= self.factor)

    def history_point(self, samples):
        """The value the engine appends to this rule's history after a
        tick (None = nothing to record)."""
        if self.kind == "rate":
            rows = samples.get(self.family)
            if rows is None:
                return None  # family unknown yet: no baseline point
            return self._reduce(rows)
        if self.kind == "burn_rate":
            bad = self._reduce(samples.get(self.family) or [])
            total = self._reduce(samples.get(self.total_family) or [])
            if bad is None and total is None:
                return None
            return (bad or 0.0, total or 0.0)
        return None

    def describe(self):
        d = {"name": self.name, "kind": self.kind, "family": self.family,
             "severity": self.severity, "op": self.op, "value": self.value,
             "for_s": self.for_s, "cooldown_s": self.cooldown_s,
             "reduce": self.reduce, "doc": self.doc}
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.kind == "rate":
            d["window_s"] = self.window_s
        if self.kind == "burn_rate":
            d.update({"total_family": self.total_family,
                      "objective": self.objective, "factor": self.factor,
                      "fast_s": self.fast_s, "slow_s": self.slow_s})
        return d


# -- sample sources ------------------------------------------------------------
_PROBES = {}


def register_probe(family, fn):
    """Install a cheap read probe for a family that is not a plain
    registry metric (collector-backed signals).  ``fn()`` -> list of
    ``(labels_dict, value)``."""
    _PROBES[str(family)] = fn


def _serving_counter_probe(key):
    def probe():
        mod = sys.modules.get("mxnet_tpu.serving.metrics")
        if mod is None:
            return []
        out = []
        for name, snap in mod.stats().items():
            v = snap.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out.append(({"server": name}, float(v)))
        return out
    return probe


def _register_default_probes():
    from . import watchdog
    register_probe("mxnet_watchdog_stalled_sections",
                   lambda: [({}, float(len(watchdog.stalled_sections())))])
    register_probe("mxnet_watchdog_fires_total",
                   lambda: [({}, float(watchdog.fires()))])
    register_probe("mxnet_serving_requests_total",
                   _serving_counter_probe("requests_total"))
    register_probe("mxnet_serving_shed_total",
                   _serving_counter_probe("shed_total"))

    def rss_slope_probe():
        from . import resources
        return [({}, float(resources.leak_slope()))]
    register_probe("mxnet_resource_rss_slope_bytes_per_s", rss_slope_probe)

    def snapshot_age_probe():
        from . import fleet
        fn = fleet.provider()
        if fn is None:
            return []
        snap = fn() or {}
        out = []
        if snap.get("mode") == "summary":
            # summary-mode leader (world > DETAIL_AUTO_RANKS): the
            # scrape carries the fleet-wide max age + per-rank ages for
            # anomalous ranks only — exactly what a reduce=max
            # staleness rule needs, without the O(ranks) row fan-out
            age = snap.get("snapshot_age_max_s")
            if isinstance(age, (int, float)):
                out.append(({}, float(age)))
            for rank, v in (snap.get("anomalous") or {}).items():
                age = v.get("snapshot_age_s")
                if isinstance(age, (int, float)):
                    out.append(({"rank": str(rank)}, float(age)))
            return out
        for rank, v in snap.get("ranks", {}).items():
            age = v.get("snapshot_age_s")
            if isinstance(age, (int, float)):
                out.append(({"rank": str(rank)}, float(age)))
        return out
    register_probe("mxnet_fleet_snapshot_age_seconds", snapshot_age_probe)

    def data_queue_depth_probe():
        # pull, never import: a process with no streaming data plane
        # has no rows.  Live pipelines answer only while they make
        # progress — a wedged assembler lets the family go ABSENT, so
        # an absence rule on mxnet_data_queue_depth fires while the
        # train/fit watchdog walks up to its page (docs/data.md)
        mod = sys.modules.get("mxnet_tpu.io_pipeline")
        if mod is None:
            return []
        return mod.queue_depth_samples()
    register_probe("mxnet_data_queue_depth", data_queue_depth_probe)


def _read_family(family):
    probe = _PROBES.get(family)
    if probe is not None:
        try:
            return [(dict(labels), float(v)) for labels, v in probe()]
        except Exception as e:  # noqa: BLE001 — one dead probe must not poison the tick
            log.debug("alert probe %r failed: %s", family, e)
            return []
    from . import REGISTRY
    m = REGISTRY.get(family)
    rows = None
    if m is None and (family.endswith("_count") or family.endswith("_sum")):
        base = family.rsplit("_", 1)[0]
        h = REGISTRY.get(base)
        if h is not None and h.kind == "histogram":
            rows = [s for s in h._samples() if s[0] == family]
    elif m is not None:
        rows = [s for s in m._samples() if s[0] == family]
    if rows is None:
        return None
    return [(dict(s[1]), float(s[2])) for s in rows]


def registry_sampler(families):
    """The default sample source: registered probes first, then plain
    registry metrics (histograms answer for their ``_count``/``_sum``
    derived families).  Unknown families read as absent."""
    out = {}
    for fam in families:
        rows = _read_family(fam)
        if rows is not None:
            out[fam] = rows
    return out


# -- the default rule pack -----------------------------------------------------
def default_rules():
    """The doc alarm table as code (docs/observability.md 'Default rule
    pack'): each entry names the counter it judges and the degraded
    mode it pages on."""
    return [
        AlertRule(
            "watchdog_stall", "mxnet_watchdog_stalled_sections",
            kind="threshold", op=">", value=0, for_s=0.0, cooldown_s=30.0,
            severity="page",
            doc="an armed section is in an active stall episode (the "
                "watchdog fired and no progress since); resolves the "
                "moment the section beats"),
        AlertRule(
            "corrupt_checkpoint", "mxnet_serving_corrupt_ckpt_total",
            kind="rate", op=">", value=0.0, window_s=60.0, for_s=0.0,
            cooldown_s=60.0, severity="page",
            doc="a committed checkpoint step failed verification during "
                "hot-reload within the last window; the old version "
                "keeps serving but publishes are broken"),
        AlertRule(
            "spill_storm", "mxnet_serving_router_spill_total",
            kind="rate", op=">", value=1.0, window_s=10.0, for_s=2.0,
            cooldown_s=30.0, severity="warn",
            doc="sustained router spill rate: a replica is persistently "
                "refusing traffic while siblings absorb it"),
        AlertRule(
            "shed_burn_rate", "mxnet_serving_shed_total",
            kind="burn_rate", total_family="mxnet_serving_requests_total",
            objective=0.05, factor=2.0, fast_s=60.0, slow_s=300.0,
            for_s=0.0, cooldown_s=120.0, severity="page",
            doc="shed-ratio SLO burn: sheds are consuming the 5% error "
                "budget at >= 2x in BOTH the fast and slow windows"),
        AlertRule(
            "retrace_ratchet", "mxnet_compile_traces_total",
            kind="rate", op=">", value=0.5, window_s=30.0, for_s=10.0,
            cooldown_s=120.0, severity="warn",
            labels={"reason": "request"},
            doc="sustained REQUEST-path retraces: compiles are running "
                "on the hot path (deliberate warmup/build traces are "
                "excluded by the reason label; docs/compile.md runbook)"),
        AlertRule(
            "rss_slope", "mxnet_resource_rss_slope_bytes_per_s",
            kind="threshold", op=">", value=8e6, for_s=10.0,
            cooldown_s=120.0, severity="warn",
            doc="host RSS climbing at > 8 MB/s over the sampler window "
                "— a leak, or a workload outgrowing the host"),
        AlertRule(
            "snapshot_stale", "mxnet_fleet_snapshot_age_seconds",
            kind="threshold", op=">", value=30.0, for_s=5.0,
            cooldown_s=60.0, severity="warn", reduce="max",
            doc="a fleet rank's last telemetry push is stale: its "
                "reporter wedged or the rank is dying quietly"),
        AlertRule(
            "fleet_merge_slow", "mxnet_fleet_merge_seconds_sum",
            kind="rate", op=">", value=0.05, window_s=30.0, for_s=10.0,
            cooldown_s=120.0, severity="warn",
            doc="the fleet leader is spending a sustained > 5% of wall "
                "time merging telemetry pushes (merge seconds accruing "
                "at > 0.05 s/s over the lookback): delta encoding is "
                "off/ineffective or the store is degenerating to full "
                "re-merges — docs/observability.md 'the leader is hot' "
                "runbook"),
        AlertRule(
            "nonfinite_window", "mxnet_numerics_nonfinite_windows_total",
            kind="rate", op=">", value=0.0, window_s=60.0, for_s=0.0,
            cooldown_s=60.0, severity="page",
            doc="a train window contained non-finite gradients/params/"
                "loss within the last minute: the model is diverging or "
                "the data is poisoned — the forensic "
                "mxnet-numerics-*.json dump names the window "
                "(docs/observability.md numerics runbook)"),
        AlertRule(
            "grad_norm_explosion", "mxnet_numerics_grad_norm",
            kind="rate", op=">", value=1.0, window_s=30.0, for_s=5.0,
            cooldown_s=120.0, severity="warn",
            doc="the global gradient norm is climbing sustainedly "
                "(> 1/s over the lookback): an exploding-gradient "
                "trajectory headed for non-finite; tune the bound to "
                "the model's scale via MXNET_ALERT_RULES"),
        AlertRule(
            "loss_spike", "mxnet_numerics_loss",
            kind="rate", op=">", value=0.5, window_s=30.0, for_s=5.0,
            cooldown_s=120.0, severity="warn",
            doc="the loss proxy is rising sustainedly instead of "
                "converging — divergence judged before it reaches "
                "non-finite; tune the bound per model via "
                "MXNET_ALERT_RULES"),
        AlertRule(
            "data_starved", "mxnet_data_wait_seconds_sum",
            kind="rate", op=">", value=0.3, window_s=30.0, for_s=10.0,
            cooldown_s=120.0, severity="warn",
            doc="the train thread is spending a sustained > 30% of "
                "wall time blocked on the input pipeline (data_wait "
                "seconds accruing at > 0.3 s/s over the lookback): "
                "training is data-bound — raise MXNET_DATA_WORKERS / "
                "queue depth or shrink the decode (docs/data.md "
                "'training is data-bound' runbook)"),
        AlertRule(
            "kernel_fallback", "mxnet_kernel_fallback_total",
            kind="rate", op=">", value=0.0, window_s=60.0, for_s=0.0,
            cooldown_s=120.0, severity="warn",
            doc="a kernels-subsystem lookup served the reference "
                "implementation instead of a Pallas config within the "
                "last window — a correctness-gate failure or aborted "
                "autotune (docs/kernels.md runbook); numerics stay "
                "correct, the tuned speed is gone"),
    ]


def parse_rules(spec):
    """``MXNET_ALERT_RULES`` grammar — ``;``-separated arms::

        name=family>value[:for=S][:cooldown=S][:severity=warn|page]
                         [:reduce=sum|max|min][:kind=threshold|rate|absence]
                         [:window=S]

    (``<`` for lower bounds).  Parsed rules are appended to the default
    pack; a name collision replaces the default rule.
    """
    rules = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(f"alert rule spec {part!r}: expected "
                             "name=family<op>value[...]")
        name, rhs = part.split("=", 1)
        fields = rhs.split(":")
        head, opts = fields[0].strip(), fields[1:]
        op = ">" if ">" in head else ("<" if "<" in head else None)
        if op is None:
            raise MXNetError(f"alert rule spec {part!r}: no > or < bound")
        family, value = head.split(op, 1)
        kw = {"op": op, "value": float(value)}
        keymap = {"for": ("for_s", float), "cooldown": ("cooldown_s", float),
                  "severity": ("severity", str), "reduce": ("reduce", str),
                  "kind": ("kind", str), "window": ("window_s", float)}
        for opt in opts:
            if "=" not in opt:
                raise MXNetError(f"alert rule spec {part!r}: bad option "
                                 f"{opt!r}")
            k, v = opt.split("=", 1)
            if k.strip() not in keymap:
                raise MXNetError(f"alert rule spec {part!r}: unknown "
                                 f"option {k!r}")
            field, cast = keymap[k.strip()]
            kw[field] = cast(v.strip())
        rules.append(AlertRule(name.strip(), family.strip(), **kw))
    return rules


# -- the engine ----------------------------------------------------------------
_HISTORY_POINTS = 2048
_TRANSITIONS_KEPT = 16


class AlertEngine:
    """Evaluates a rule set against a sample source; owns each rule's
    lifecycle state.  ``tick(now=...)`` takes an explicit clock so the
    hysteresis / cooldown / burn-window tests are deterministic."""

    def __init__(self, rules=None, sampler=None):
        _register_default_probes()  # idempotent: default-pack sources
        self.rules = list(default_rules() if rules is None else rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise MXNetError(f"duplicate alert rule names: {names}")
        self._sampler = sampler if sampler is not None else registry_sampler
        self._lock = threading.Lock()
        # serializes tick() bodies (the per-rule history deques are
        # single-writer) WITHOUT holding self._lock across user rule
        # code — self._lock guards engine state only and is never held
        # while rule.evaluate/describe or another subsystem runs
        self._tick_lock = threading.Lock()
        self._states = {r.name: self._fresh_state() for r in self.rules}
        self._history = {r.name: collections.deque(maxlen=_HISTORY_POINTS)
                         for r in self.rules}
        self.ticks = 0
        self._metrics_ready = False

    @staticmethod
    def _fresh_state():
        return {"state": "inactive", "since": None, "pending_since": None,
                "fired_at": None, "resolved_at": None, "value": None,
                "transitions": 0, "fired_total": 0,
                "recent": collections.deque(maxlen=_TRANSITIONS_KEPT)}

    def add_rule(self, rule, replace=True):
        with self._lock:
            for i, r in enumerate(self.rules):
                if r.name == rule.name:
                    if not replace:
                        raise MXNetError(f"alert rule {rule.name!r} exists")
                    self.rules[i] = rule
                    break
            else:
                self.rules.append(rule)
            self._states.setdefault(rule.name, self._fresh_state())
            self._history.setdefault(
                rule.name, collections.deque(maxlen=_HISTORY_POINTS))

    # -- metrics side effects ------------------------------------------------
    def _metrics(self):
        from . import REGISTRY
        return (REGISTRY.counter(
                    "mxnet_alert_transitions_total",
                    "alert rule lifecycle transitions, by rule and "
                    "target state"),
                REGISTRY.gauge(
                    "mxnet_alert_state",
                    "one-hot alert rule state (1 = the labelled state "
                    "holds), by rule"),
                REGISTRY.gauge(
                    "mxnet_alerts_firing",
                    "count of currently-firing alert rules, by severity"))

    def _transition(self, rule, st, to, now, value):
        """Mutate ``st`` (caller holds ``self._lock``) and return the
        emission record — metric/flight/log side effects run OUTSIDE
        the lock (``_emit_transition``): the flight ring, the registry,
        and the logging subsystem each own locks of their own, and
        holding the engine lock into them is an ordering edge the
        lock-order-cycle rule rightly flags."""
        frm = st["state"]
        st["state"] = to
        st["since"] = now
        st["transitions"] += 1
        st["recent"].append({"t": time.time(), "mono": now, "from": frm,
                             "to": to, "value": value})
        if to == "pending":
            st["pending_since"] = now
        elif to == "firing":
            st["fired_at"] = now
            st["fired_total"] += 1
        elif to == "resolved":
            st["resolved_at"] = now
        return (rule, frm, to, value)

    def _emit_transition(self, rule, frm, to, value):
        counter, state_gauge, _firing_gauge = self._metrics()
        counter.inc(labels={"rule": rule.name, "to": to})
        for s in STATES:
            state_gauge.set(1.0 if s == to else 0.0,
                            labels={"rule": rule.name, "state": s})
        from . import flight
        if to == "firing":
            severity = "error" if rule.severity == "page" else "warn"
        else:
            severity = "info"
        flight.record("alert", f"{rule.name}:{frm}->{to}",
                      severity=severity, rule=rule.name, to=to,
                      value=value, threshold=rule.value,
                      rule_severity=rule.severity)
        log.log(logging.WARNING if to == "firing" else logging.INFO,
                "alert %s: %s -> %s (value=%s threshold=%s)",
                rule.name, frm, to, value, rule.value)

    # -- evaluation ----------------------------------------------------------
    def tick(self, now=None):
        """One evaluation pass over every rule; returns the number of
        state transitions it caused.

        Lock protocol: ``rule.history_point``/``rule.evaluate`` are
        USER code (``add_rule`` accepts arbitrary objects) and run
        under ``_tick_lock`` only — a rule that introspects the engine
        (``state()``/``firing()``) must not deadlock on the engine
        lock.  ``self._lock`` is held only to snapshot the rule list
        and to apply state transitions; metric/flight/log emission
        happens after it is released."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            rules = list(self.rules)
        families = set()
        for r in rules:
            families |= r.families()
        try:
            samples = self._sampler(families)
        except Exception as e:  # noqa: BLE001 — a broken sampler must not kill the evaluation thread
            log.warning("alert sampler failed: %s", e)
            return 0
        moved = 0
        events = []
        with self._tick_lock:
            with self._lock:
                self.ticks += 1
                histories = {r.name: self._history[r.name]
                             for r in rules if r.name in self._history}
            evals = []
            for rule in rules:
                history = histories.get(rule.name)
                if history is None:
                    continue
                point = rule.history_point(samples)
                if point is not None:
                    history.append((now, point))
                value, cond = rule.evaluate(samples, history, now)
                evals.append((rule, value, cond))
            with self._lock:
                for rule, value, cond in evals:
                    st = self._states.get(rule.name)
                    if st is None:
                        continue
                    st["value"] = value
                    state = st["state"]
                    if state == "inactive":
                        if cond:
                            events.append(self._transition(
                                rule, st, "pending", now, value))
                            if rule.for_s <= 0:
                                events.append(self._transition(
                                    rule, st, "firing", now, value))
                    elif state == "pending":
                        if not cond:
                            events.append(self._transition(
                                rule, st, "inactive", now, value))
                        elif now - st["pending_since"] >= rule.for_s:
                            events.append(self._transition(
                                rule, st, "firing", now, value))
                    elif state == "firing":
                        if not cond:
                            events.append(self._transition(
                                rule, st, "resolved", now, value))
                    elif state == "resolved":
                        cooled = (now - (st["resolved_at"] or now)
                                  >= rule.cooldown_s)
                        if cond and cooled:
                            events.append(self._transition(
                                rule, st, "pending", now, value))
                            if rule.for_s <= 0:
                                events.append(self._transition(
                                    rule, st, "firing", now, value))
                        elif not cond and cooled:
                            events.append(self._transition(
                                rule, st, "inactive", now, value))
                counts = {s: 0 for s in SEVERITIES}
                for rule in rules:
                    st = self._states.get(rule.name)
                    if st is not None and st["state"] == "firing":
                        counts[rule.severity] += 1
            moved = len(events)
            for rule, frm, to, value in events:
                self._emit_transition(rule, frm, to, value)
            _c, _g, firing_gauge = self._metrics()
            for sev, n in counts.items():
                firing_gauge.set(n, labels={"severity": sev})
        return moved

    # -- read side -----------------------------------------------------------
    def state(self, name):
        with self._lock:
            st = self._states[name]
            return {k: (list(v) if isinstance(v, collections.deque) else v)
                    for k, v in st.items()}

    def firing(self, severity=None):
        """Names of currently-firing rules (optionally one severity)."""
        with self._lock:
            return sorted(
                r.name for r in self.rules
                if self._states[r.name]["state"] == "firing"
                and (severity is None or r.severity == severity))

    def transitions(self, name):
        with self._lock:
            return list(self._states[name]["recent"])

    def alerts_json(self):
        """The ``GET /alerts.json`` payload.  ``rule.describe()`` is
        user code and runs outside the engine lock (state is snapshot
        first)."""
        with self._lock:
            rule_list = list(self.rules)
            snap = {}
            for rule in rule_list:
                st = self._states[rule.name]
                snap[rule.name] = {
                    "state": st["state"], "value": st["value"],
                    "since": st["since"],
                    "transitions": st["transitions"],
                    "fired_total": st["fired_total"],
                    "recent": list(st["recent"])}
            ticks = self.ticks
        rules = []
        for rule in rule_list:
            d = rule.describe()
            d.update(snap[rule.name])
            rules.append(d)
        firing = sorted(r.name for r in rule_list
                        if snap[r.name]["state"] == "firing")
        pages = sorted(r.name for r in rule_list
                       if snap[r.name]["state"] == "firing"
                       and r.severity == "page")
        return {"time": time.time(), "enabled": _armed,
                "ticks": ticks, "rules": rules,
                "firing": firing, "pages": pages}


# -- module-level singleton + evaluation thread --------------------------------
def engine():
    """The process-wide engine (created on first use: default pack +
    any ``MXNET_ALERT_RULES`` extras)."""
    global _engine
    with _lock:
        if _engine is None:
            eng = AlertEngine()
            from .. import config as _config
            for rule in parse_rules(_config.get("MXNET_ALERT_RULES")):
                eng.add_rule(rule)
            _engine = eng
        return _engine


def set_engine(eng):
    """Install a specific engine as the process-wide one (tests; None
    resets to lazy default)."""
    global _engine
    with _lock:
        _engine = eng


def tick(now=None):
    """Module-level tick: one global check when the engine is disarmed
    (< 1 µs — the span/trace/failpoint bar), a full evaluation pass
    otherwise."""
    if not _armed:
        return 0
    return engine().tick(now=now)


def enabled():
    return _armed


def start(interval_s=None):
    """Arm the engine and start the evaluation thread.  ``interval_s``
    defaults to ``MXNET_ALERTS`` (0 = leave disarmed)."""
    global _armed, _thread, _stop
    if interval_s is None:
        from .. import config as _config
        interval_s = float(_config.get("MXNET_ALERTS"))
    interval_s = float(interval_s)
    if interval_s <= 0:
        return False
    eng = engine()  # build before arming: first tick must not race init
    _armed = True
    with _lock:
        if _thread is not None and _thread.is_alive():
            return True
        _stop = threading.Event()
        _thread = threading.Thread(
            target=_loop, args=(eng, interval_s), daemon=True,
            name="mx-alert-engine")
        _thread.start()
    return True


def stop():
    """Disarm and stop the evaluation thread (state is kept)."""
    global _armed, _thread, _stop
    _armed = False
    with _lock:
        stop_ev, _stop = _stop, None
        thread, _thread = _thread, None
    if stop_ev is not None:
        stop_ev.set()
    if thread is not None:
        thread.join(timeout=5)


def _loop(eng, interval_s):
    while True:
        with _lock:
            stop_ev = _stop
        if stop_ev is None or stop_ev.wait(max(0.01, interval_s)):
            return
        try:
            eng.tick()
        except Exception as e:  # noqa: BLE001 — the evaluation loop must survive any one bad tick
            log.warning("alert tick failed: %s", e)


def firing(severity=None):
    """Currently-firing rule names; cheap and safe when disarmed."""
    with _lock:
        eng = _engine
    if not _armed or eng is None:
        return []
    return eng.firing(severity)


def firing_pages():
    """Firing page-severity rules — the ``/healthz`` readiness input
    (warn-severity alerts deliberately stay out of liveness)."""
    return firing("page")


def alerts_json():
    """The ``/alerts.json`` payload (meaningful on any process: a
    disarmed engine reports its rule pack with enabled=false)."""
    return engine().alerts_json()


def _reset_for_tests():
    """Stop the thread, drop the singleton, forget probes."""
    stop()
    set_engine(None)
