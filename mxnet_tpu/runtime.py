"""Runtime feature detection (parity: python/mxnet/runtime.py +
include/mxnet/libinfo.h feature flags). Features reflect what the TPU
runtime actually provides."""
from __future__ import annotations

import collections

import jax

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    try:
        platform = jax.default_backend()
    except Exception:
        platform = "unknown"
    feats = {
        "TPU": platform in ("tpu", "axon"),
        "CPU": True,
        "CUDA": False,
        "CUDNN": False,
        "NCCL": False,
        "OPENMP": True,          # XLA threadpool
        "BLAS_OPEN": True,       # XLA dot
        "LAPACK": True,          # jax.scipy.linalg
        "MKLDNN": False,
        "XLA": True,
        "PALLAS": True,
        "F16C": True,
        "INT64_TENSOR_SIZE": False,  # int32 index space (TPU-native width)
        "SIGNAL_HANDLER": True,
        "DEBUG": False,
        "DIST_KVSTORE": True,
        "SSE": True,
        "PROFILER": True,
    }
    return {k: Feature(k, v) for k, v in feats.items()}


class Features(dict):
    """Check the library for compile-time features
    (parity: runtime.py Features)."""

    instance = None

    def __new__(cls):
        if cls.instance is None:
            cls.instance = super().__new__(cls)
            dict.__init__(cls.instance, _detect())
        return cls.instance

    def __repr__(self):
        return f"[{', '.join(f'✔ {n}' if f.enabled else f'✖ {n}' for n, f in self.items())}]"

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature '{feature_name}' is unknown, "
                               "known features are: %s" % list(self.keys()))
        return self[feature_name].enabled


def feature_list():
    return list(Features().values())
