"""Parameter-server transport for dist kvstore.

Role parity with ps-lite (reference 3rdparty/ps-lite ZeroMQ van +
src/kvstore/kvstore_dist_server.h): a server process owns the store and
aggregates pushes; workers push/pull over TCP; DMLC_* env vars drive the
rendezvous exactly like the reference (DMLC_ROLE, DMLC_PS_ROOT_URI,
DMLC_PS_ROOT_PORT, DMLC_NUM_WORKER). sync mode aggregates until all workers
pushed then applies the updater (kvstore_dist_server.h:346 ApplyUpdates);
async applies per push.

Wire format: pickle frames with a u32 length prefix — simple and sufficient
for localhost tests; multi-host TPU deployments use the SPMD path (XLA
collectives over ICI/DCN), not this server.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading

import numpy as np


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_msg(sock):
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack("<I", header)
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return pickle.loads(payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class KVServer:
    """The server process main loop (parity: KVStoreDistServer)."""

    def __init__(self, port=9091, num_workers=1):
        self.port = port
        self.num_workers = num_workers
        self.store = {}           # key -> np.ndarray
        self.updater = None
        self.optimizer = None
        self._agg = {}            # key -> (sum, count) for sync mode
        self._barrier_count = 0
        self._barrier_cv = threading.Condition()
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def run(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.port))
        srv.listen(self.num_workers * 2)
        threads = []
        try:
            while not self._stop.is_set():
                srv.settimeout(1.0)
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                t = threading.Thread(target=self._handle, args=(conn,),
                                     daemon=True)
                t.start()
                threads.append(t)
        finally:
            srv.close()

    def _apply_update(self, key, grad):
        """sync aggregate-then-update / async per-push update
        (parity: DataHandleEx kvstore_dist_server.h:325)."""
        if self.updater is None:
            # no optimizer installed: store accumulates the pushed value
            self.store[key] = grad.copy()
            return
        stored = self.store[key]
        self.updater(key, grad, stored)

    def _handle(self, conn):
        while not self._stop.is_set():
            msg = _recv_msg(conn)
            if msg is None:
                break
            op = msg["op"]
            if op == "init":
                with self._lock:
                    if msg["key"] not in self.store:
                        self.store[msg["key"]] = np.array(msg["value"])
                _send_msg(conn, {"ok": True})
            elif op == "push":
                key = msg["key"]
                grad = np.asarray(msg["value"])
                with self._lock:
                    if msg.get("sync", True):
                        s, c = self._agg.get(key, (None, 0))
                        s = grad if s is None else s + grad
                        c += 1
                        if c == self.num_workers:
                            self._apply_update(key, s)
                            self._agg[key] = (None, 0)
                        else:
                            self._agg[key] = (s, c)
                    else:
                        self._apply_update(key, grad)
                _send_msg(conn, {"ok": True})
            elif op == "pull":
                with self._lock:
                    val = self.store.get(msg["key"])
                _send_msg(conn, {"ok": True, "value": val})
            elif op == "barrier":
                with self._barrier_cv:
                    self._barrier_count += 1
                    gen = self._barrier_count // self.num_workers
                    if self._barrier_count % self.num_workers == 0:
                        self._barrier_cv.notify_all()
                    else:
                        target = (self._barrier_count // self.num_workers) + 1
                        self._barrier_cv.wait_for(
                            lambda: self._barrier_count >=
                            target * self.num_workers, timeout=120)
                _send_msg(conn, {"ok": True})
            elif op == "command":
                head, body = msg["head"], msg["body"]
                if head == "set_optimizer":
                    from . import optimizer as opt_mod
                    self.optimizer = pickle.loads(body)
                    updater = opt_mod.get_updater(self.optimizer)

                    def np_updater(key, grad_np, stored_np, _u=updater):
                        from . import ndarray as nd
                        g = nd.array(grad_np)
                        w = nd.array(stored_np)
                        _u(key, g, w)
                        stored_np[...] = w.asnumpy()
                    self.updater = np_updater
                elif head == "stop":
                    self._stop.set()
                _send_msg(conn, {"ok": True})
            else:
                _send_msg(conn, {"ok": False, "error": f"bad op {op}"})
        conn.close()


class KVClient:
    """Worker-side connection (parity: ps::KVWorker)."""

    def __init__(self, host, port, rank, num_workers, timeout=120):
        self.rank = rank
        self.num_workers = num_workers
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.settimeout(timeout)
        import time
        deadline = time.time() + timeout
        while True:
            try:
                self.sock.connect((host, port))
                break
            except (ConnectionRefusedError, socket.timeout):
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
        self._lock = threading.Lock()

    def _rpc(self, msg):
        with self._lock:
            _send_msg(self.sock, msg)
            resp = _recv_msg(self.sock)
        if resp is None or not resp.get("ok"):
            raise RuntimeError(f"kvstore server rpc failed: {resp}")
        return resp

    def init(self, key, value):
        self._rpc({"op": "init", "key": key, "value": np.asarray(value)})

    def push(self, key, value, sync=True):
        self._rpc({"op": "push", "key": key, "value": np.asarray(value),
                   "sync": sync})

    def pull(self, key):
        return self._rpc({"op": "pull", "key": key})["value"]

    def barrier(self):
        self._rpc({"op": "barrier"})

    def send_command(self, head, body):
        self._rpc({"op": "command", "head": head, "body": body})

    def stop_server(self):
        self._rpc({"op": "command", "head": "stop", "body": b""})


def run_server_from_env():
    """Entry for DMLC_ROLE=server processes (parity:
    python/mxnet/kvstore_server.py _init_kvstore_server_module)."""
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", 9091))
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", 1))
    server = KVServer(port=port, num_workers=num_workers)
    server.run()


if __name__ == "__main__":
    run_server_from_env()
