"""Parameter-server transport for dist kvstore.

Role parity with ps-lite (reference 3rdparty/ps-lite ZeroMQ van +
src/kvstore/kvstore_dist_server.h): a server process owns the store and
aggregates pushes; workers push/pull over TCP; DMLC_* env vars drive the
rendezvous exactly like the reference (DMLC_ROLE, DMLC_PS_ROOT_URI,
DMLC_PS_ROOT_PORT, DMLC_NUM_WORKER). sync mode aggregates until all workers
pushed then applies the updater (kvstore_dist_server.h:346 ApplyUpdates);
async applies per push.

Wire format: pickle frames, u32 length prefix + HMAC-SHA256 of the body
(keyed by MXNET_KVSTORE_AUTH_TOKEN, verified before deserializing).
Localhost-only by default; multi-host TPU deployments use the SPMD path
(XLA collectives over ICI/DCN), not this server.
"""
from __future__ import annotations

import hmac
import hashlib
import logging
import os
import pickle
import random
import socket
import struct
import threading
import time

import numpy as np

from .base import PeerLostError
from .chaos.failpoints import failpoint as _failpoint

# pickle frames execute code on load: every frame carries an HMAC-SHA256 of
# the body keyed by MXNET_KVSTORE_AUTH_TOKEN, VERIFIED BEFORE deserializing.
# With no token configured the MAC is all-zeros and the server must only
# listen on localhost (the default bind).
_MAC_LEN = 32


def _max_frame():
    from .config import get as _cfg
    return int(_cfg("MXNET_KVSTORE_MAX_FRAME"))


def _token():
    from .config import get as _cfg
    return _cfg("MXNET_KVSTORE_AUTH_TOKEN")


def _mac(body, token):
    if not token:
        return b"\x00" * _MAC_LEN
    return hmac.new(token.encode(), body, hashlib.sha256).digest()


def _send_msg(sock, obj, token=None):
    """Send one framed message; returns the wire frame size (length
    header + MAC + pickled body) so callers can do byte accounting
    without serializing the object a second time."""
    token = _token() if token is None else token
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    frame = struct.pack("<I", len(body)) + _mac(body, token) + body
    sock.sendall(frame)
    return len(frame)


def _recv_msg(sock, token=None):
    token = _token() if token is None else token
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack("<I", header)
    if length > _max_frame():
        raise RuntimeError(f"kvstore frame too large: {length}")
    mac = _recv_exact(sock, _MAC_LEN)
    if mac is None:
        return None
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    if not hmac.compare_digest(mac, _mac(payload, token)):
        # authenticate BEFORE pickle.loads — never deserialize an
        # unauthenticated frame
        raise RuntimeError("kvstore frame failed authentication")
    return pickle.loads(payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class KVServer:
    """The server process main loop (parity: KVStoreDistServer)."""

    def __init__(self, port=9091, num_workers=1, bind_addr=None,
                 auth_token=None, peer_timeout_s=None, clock=None):
        self.port = port
        # liveness/telemetry clock hook: the fleet simulator injects a
        # virtual clock so 1000-rank aging scenarios run in-process in
        # seconds; production always uses time.monotonic
        self._clock = clock if clock is not None else time.monotonic
        # explicit dead-peer threshold override (the elastic launcher's
        # control plane runs tighter than the training-store default)
        self.peer_timeout_s = peer_timeout_s
        # localhost-only by default: frames are pickle (code execution if a
        # hostile peer can reach the port).  Cross-host deployments must set
        # DMLC_PS_BIND_ADDR explicitly AND share MXNET_KVSTORE_AUTH_TOKEN.
        self.bind_addr = bind_addr if bind_addr is not None else \
            os.environ.get("DMLC_PS_BIND_ADDR", "127.0.0.1")
        self.auth_token = auth_token if auth_token is not None else \
            os.environ.get("MXNET_KVSTORE_AUTH_TOKEN", "")
        from .config import get as _cfg
        if (self.bind_addr not in ("127.0.0.1", "localhost", "::1")
                and not self.auth_token
                and not _cfg("MXNET_KVSTORE_ALLOW_INSECURE")):
            raise RuntimeError(
                "KVServer: refusing to bind a non-loopback address "
                f"({self.bind_addr}) without MXNET_KVSTORE_AUTH_TOKEN — "
                "unauthenticated pickle frames are remote code execution. "
                "Set a token, or MXNET_KVSTORE_ALLOW_INSECURE=1 on a "
                "trusted private network.")
        self.num_workers = num_workers
        self.controller = None  # MXKVStoreRunServer hook
        self.store = {}           # key -> np.ndarray
        self.updater = None
        self.optimizer = None
        # failure detection (parity: ps-lite heartbeats surfaced as
        # KVStore::get_num_dead_node, include/mxnet/kvstore.h:353)
        self._heartbeats = {}     # rank -> last heartbeat monotonic time
        self._progress = {}       # rank -> last reported step
        # dead-peer propagation (ISSUE 11): ranks that heartbeated then
        # went silent past MXNET_KVSTORE_PEER_TIMEOUT_S.  The Event is
        # the lock-free predicate blocked pull/barrier waiters poll; the
        # dict (under _lock) carries which ranks for the typed reply.
        self._dead = {}           # rank -> monotonic time marked lost
        self._dead_event = threading.Event()
        self._start_time = self._clock()
        # cross-rank telemetry aggregation (ISSUE 12 / sharded since
        # ISSUE 20): per-(generation, rank) payloads live in a lazily
        # created telemetry.fleet.FleetStore (incremental delta upserts,
        # capped generation history, summary rollup aggregates)
        self._generation = 0
        self._fleet_store = None  # telemetry.fleet.FleetStore, lazy
        # port=0 binds an OS-assigned port (port-collision-safe tests /
        # supervisor-owned control planes); bound_port is readable after
        # the started event sets
        self.bound_port = None if port == 0 else port
        self.started = threading.Event()
        self._agg = {}            # key -> (sum, count) for sync mode
        self._version = {}        # key -> completed sync rounds
        self._barrier_count = 0
        self._barrier_cv = threading.Condition()
        self._lock = threading.Lock()
        # signaled whenever a sync aggregation round completes, so pulls can
        # wait out an in-flight round (parity: the reference server buffers
        # pull responses until ApplyUpdates runs, kvstore_dist_server.h:346)
        self._store_cv = threading.Condition(self._lock)
        self._stop = threading.Event()

    def run(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.bind_addr, self.port))
        self.bound_port = self.port = srv.getsockname()[1]
        with self._lock:  # num_workers is rewritten by reset_world
            backlog = max(4, self.num_workers * 2)
        srv.listen(backlog)
        self.started.set()
        threads = []
        monitor = threading.Thread(target=self._peer_monitor, daemon=True)
        monitor.start()
        try:
            while not self._stop.is_set():
                srv.settimeout(1.0)
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                t = threading.Thread(target=self._handle, args=(conn,),
                                     daemon=True)
                t.start()
                threads.append(t)
        finally:
            srv.close()

    # -- dead-peer propagation (ISSUE 11) -----------------------------------
    def _peer_timeout(self):
        if self.peer_timeout_s is not None:
            return float(self.peer_timeout_s)
        from .config import get as _cfg
        return float(_cfg("MXNET_KVSTORE_PEER_TIMEOUT_S"))

    def _peer_monitor(self):
        """Mark ranks lost when their heartbeats age out, and WAKE every
        blocked waiter (versioned pulls, barriers) so in-flight RPCs
        that need a dead rank fail with typed PeerLostError instead of
        waiting out their generic timeouts against a corpse.  Only ranks
        that announced themselves at least once are eligible — silence
        from a rank that never heartbeated means heartbeating is off."""
        while not self._stop.wait(0.1):
            timeout = self._peer_timeout()
            now = self._clock()
            newly_dead = False
            with self._lock:
                for rank, last in self._heartbeats.items():
                    if rank in self._dead:
                        continue
                    if now - last > timeout:
                        self._dead[rank] = now
                        newly_dead = True
                dead = sorted(self._dead)
            if newly_dead:
                # the Event is self-synchronized; set it before waking
                # the condition waiters so their predicates observe it
                self._dead_event.set()
                logging.getLogger("mxnet_tpu.kvstore").warning(
                    "kvstore server: peer(s) %s lost (no heartbeat for "
                    "> %.1fs); failing their in-flight waiters typed",
                    dead, timeout)
                from .telemetry import flight as _flight
                _flight.record("kvstore", "peer_lost", severity="error",
                               ranks=dead, timeout_s=timeout)
                with self._store_cv:
                    self._store_cv.notify_all()
                with self._barrier_cv:
                    self._barrier_cv.notify_all()

    def dead_ranks(self):
        with self._lock:
            return sorted(self._dead)

    def _peer_states(self):
        timeout = self._peer_timeout()
        now = self._clock()
        with self._lock:
            out = {}
            for rank in range(self.num_workers):
                last = self._heartbeats.get(rank)
                if rank in self._dead:
                    state = "lost"
                elif last is None:
                    state = "unknown"
                else:
                    state = "alive" if now - last <= timeout else "lost"
                out[rank] = {"state": state,
                             "age_s": None if last is None else now - last,
                             "step": self._progress.get(rank, 0)}
            return out

    def reset_world(self, num_workers, generation=None):
        """Re-arm the liveness layer for a new elastic world generation
        (the launcher calls this between respawns): new worker count,
        forgotten heartbeats/progress/dead marks, fresh barrier.
        Telemetry payloads are generation-keyed and KEPT (up to the
        MXNET_FLEET_HISTORY cap — a runaway restart loop must not grow
        the server without bound) — the fleet history must show every
        retained generation's ranks, lost ones tagged."""
        with self._lock:
            self.num_workers = int(num_workers)
            self._heartbeats.clear()
            self._progress.clear()
            self._dead.clear()
            self._start_time = self._clock()
            self._generation = (self._generation + 1 if generation is None
                                else int(generation))
            generation = self._generation
        self.fleet_store().set_generation(generation)
        self._dead_event.clear()
        with self._barrier_cv:
            self._barrier_count = 0
            self._barrier_cv.notify_all()

    def fleet_store(self):
        """The server's sharded telemetry store (ISSUE 20), created
        lazily so a kvstore without fleet traffic never pays for it."""
        from .telemetry.fleet import FleetStore
        with self._lock:
            if self._fleet_store is None:
                self._fleet_store = FleetStore(
                    clock=self._clock, generation=self._generation)
            return self._fleet_store

    def apply_telemetry_push(self, rank, payload):
        """The ``telemetry_push`` op body: decode a full/delta payload
        into the fleet store.  A real method (not inlined in _handle)
        so the in-process fleet simulator drives the exact production
        merge path without a socket per synthetic rank."""
        with self._lock:
            generation = self._generation
        return self.fleet_store().apply_push(
            generation, int(rank), payload or {})

    def _peer_lost_reply(self):
        return {"ok": False, "error_type": "PeerLostError",
                "dead_ranks": self.dead_ranks(),
                "error": f"peer(s) {self.dead_ranks()} lost — the "
                         "requested wait can never complete"}

    def _apply_update(self, key, grad):
        """sync aggregate-then-update / async per-push update
        (parity: DataHandleEx kvstore_dist_server.h:325).

        Callers hold ``self._lock`` — this helper is only reached from
        the push paths inside ``with self._lock:`` blocks in _handle.
        """
        if self.updater is None:
            # no optimizer installed: store accumulates the pushed value
            # graftlint: disable=lock-discipline -- caller holds self._lock
            self.store[key] = grad.copy()
            return
        # graftlint: disable=lock-discipline -- caller holds self._lock
        stored = self.store[key]
        self.updater(key, grad, stored)

    @staticmethod
    def _server_trace_filename(name):
        """The server's trace path for a given base filename: insert
        ``_server`` before the extension (idempotent), so a colocated
        server can never clobber the worker's own trace."""
        root, ext = os.path.splitext(name)
        if root.endswith("_server"):
            return name
        return f"{root}_server{ext}"

    def _profiler_command(self, head, payload):
        """Server-side profiler commands (parity: reference
        KVStoreServerProfilerCommand kSetConfig/kState/kDumpProfile,
        include/mxnet/kvstore.h:49)."""
        from . import profiler
        if head == "profiler_set_config":
            cfg = dict(payload)
            if "filename" in cfg:
                cfg["filename"] = self._server_trace_filename(
                    cfg["filename"])
            profiler.set_config(**cfg)
        elif head == "profiler_set_state":
            profiler.set_state(payload)
        elif head == "profiler_dump":
            # enforce the _server suffix even when the worker never sent
            # a filename (default config would collide on a shared CWD)
            profiler.set_config(filename=self._server_trace_filename(
                profiler.KWARGS["filename"]))
            profiler.dump(finished=payload)
        else:
            raise ValueError(f"unknown profiler command {head!r}")

    def _handle(self, conn):
        while not self._stop.is_set():
            try:
                msg = _recv_msg(conn, self.auth_token)
            except RuntimeError:
                break  # unauthenticated or oversized frame: drop connection
            if msg is None:
                break
            op = msg["op"]
            if op == "init":
                with self._lock:
                    if msg["key"] not in self.store:
                        self.store[msg["key"]] = np.array(msg["value"])
                _send_msg(conn, {"ok": True}, self.auth_token)
            elif op == "push":
                key = msg["key"]
                value = msg["value"]
                if isinstance(value, dict) and "q2bit" in value:
                    # 2-bit compressed push: unpack ±threshold/0 before
                    # aggregation (parity: kvstore_dist_server.h
                    # DataHandleCompressed)
                    from .gradient_compression import GradientCompression
                    grad = GradientCompression.decode_push(value)
                elif isinstance(value, dict) and "indices" in value:
                    # row_sparse push: only (indices, values) crossed the
                    # wire (parity: kvstore_dist.h row_sparse push); expand
                    # to a dense contribution for aggregation
                    grad = np.zeros(value["shape"],
                                    dtype=value["values"].dtype)
                    np.add.at(grad, value["indices"].astype(np.int64),
                              value["values"])
                else:
                    grad = np.asarray(value)
                with self._lock:
                    if msg.get("sync", True):
                        s, c = self._agg.get(key, (None, 0))
                        s = grad if s is None else s + grad
                        c += 1
                        if c == self.num_workers:
                            self._apply_update(key, s)
                            self._agg[key] = (None, 0)
                            self._version[key] = \
                                self._version.get(key, 0) + 1
                            self._store_cv.notify_all()
                        else:
                            self._agg[key] = (s, c)
                    else:
                        self._apply_update(key, grad)
                _send_msg(conn, {"ok": True}, self.auth_token)
            elif op == "pull":
                key = msg["key"]
                # versioned pull: the client states how many sync rounds it
                # has contributed to for this key; answering before the
                # server has applied that round would hand back PRE-update
                # weights (workers diverge).  A plain "no round in flight"
                # predicate would deadlock when a fast worker opens round
                # N+1 while a slow one still waits on round N.
                min_version = int(msg.get("min_version", 0))
                with self._store_cv:
                    # must be shorter than the client's 120s socket timeout
                    # so the error reply reaches the client instead of a
                    # socket.timeout that desynchronizes the connection.
                    # A dead peer wakes the wait: a sync round missing a
                    # lost rank's push can never complete, so the waiter
                    # fails typed instead of burning the full timeout.
                    done = self._store_cv.wait_for(
                        lambda: self._version.get(key, 0) >= min_version
                        or self._dead_event.is_set(),
                        timeout=100)
                    satisfied = self._version.get(key, 0) >= min_version
                    val = self.store.get(key)
                if done and not satisfied:
                    _send_msg(conn, self._peer_lost_reply(),
                              self.auth_token)
                elif not done:
                    _send_msg(conn, {"ok": False,
                                     "error": f"pull timeout waiting for "
                                              f"round {min_version} of key "
                                              f"{key}"}, self.auth_token)
                else:
                    rows = msg.get("rows")
                    if rows is not None and val is not None:
                        # row_sparse pull: ship only the requested rows
                        val = val[np.asarray(rows).astype(np.int64)]
                    _send_msg(conn, {"ok": True, "value": val},
                              self.auth_token)
            elif op == "heartbeat":
                try:
                    _failpoint("kvstore/server/heartbeat")
                except Exception as e:  # noqa: BLE001 — injected fault
                    logging.getLogger("mxnet_tpu.kvstore").warning(
                        "chaos: dropping heartbeat connection (%s)", e)
                    break
                with self._lock:
                    self._heartbeats[int(msg["rank"])] = self._clock()
                    if "step" in msg:
                        self._progress[int(msg["rank"])] = int(msg["step"])
                _send_msg(conn, {"ok": True}, self.auth_token)
            elif op == "progress":
                with self._lock:
                    self._progress[int(msg["rank"])] = int(msg["step"])
                _send_msg(conn, {"ok": True}, self.auth_token)
            elif op == "peer_states":
                _send_msg(conn, {"ok": True, "value": self._peer_states()},
                          self.auth_token)
            elif op == "telemetry_push":
                resp = self.apply_telemetry_push(
                    msg["rank"], msg.get("payload"))
                _send_msg(conn, resp, self.auth_token)
            elif op == "fleet":
                from .telemetry import fleet as _fleet
                _send_msg(conn, {"ok": True,
                                 "value": _fleet.merge_server(self)},
                          self.auth_token)
            elif op == "num_dead_node":
                timeout = float(msg.get("timeout", 60))
                now = self._clock()
                from .config import get as _cfg
                hb_enabled = _cfg("MXNET_KVSTORE_HEARTBEAT_INTERVAL") > 0
                with self._lock:
                    dead = 0
                    for rank in range(self.num_workers):
                        last = self._heartbeats.get(rank)
                        if last is None:
                            # never announced: dead once the grace period
                            # from server start elapses — but only when
                            # heartbeating is enabled at all, else every
                            # healthy worker would read as dead
                            if hb_enabled and \
                                    now - self._start_time > timeout:
                                dead += 1
                        elif now - last > timeout:
                            dead += 1
                _send_msg(conn, {"ok": True, "value": dead},
                          self.auth_token)
            elif op == "barrier":
                if self._dead_event.is_set():
                    # a barrier over a world with a lost rank can never
                    # fill: fail typed immediately, never hang a survivor
                    _send_msg(conn, self._peer_lost_reply(),
                              self.auth_token)
                    continue
                deadline = float(msg.get("deadline", 120))
                lost = False
                with self._barrier_cv:
                    self._barrier_count += 1
                    gen = self._barrier_count // self.num_workers
                    if self._barrier_count % self.num_workers == 0:
                        self._barrier_cv.notify_all()
                    else:
                        target = (self._barrier_count // self.num_workers) + 1
                        filled = self._barrier_cv.wait_for(
                            lambda: self._barrier_count >=
                            target * self.num_workers
                            or self._dead_event.is_set(),
                            timeout=deadline)
                        lost = (self._barrier_count <
                                target * self.num_workers
                                and self._dead_event.is_set() and filled)
                if lost:
                    _send_msg(conn, self._peer_lost_reply(),
                              self.auth_token)
                else:
                    _send_msg(conn, {"ok": True}, self.auth_token)
            elif op == "command":
                head, body = msg["head"], msg["body"]
                if head == "set_optimizer":
                    from . import optimizer as opt_mod
                    self.optimizer = pickle.loads(body)
                    updater = opt_mod.get_updater(self.optimizer)

                    def np_updater(key, grad_np, stored_np, _u=updater):
                        from . import ndarray as nd
                        g = nd.array(grad_np)
                        w = nd.array(stored_np)
                        _u(key, g, w)
                        stored_np[...] = w.asnumpy()
                    self.updater = np_updater
                    self._updater_obj = updater
                elif head == "get_optimizer_states":
                    # dist checkpoint/resume: the updater state lives
                    # HERE (update_on_kvstore), so rank 0 fetches it over
                    # the wire for the checkpoint blob
                    u = getattr(self, "_updater_obj", None)
                    if u is None:
                        _send_msg(conn, {"ok": False,
                                         "error": "no optimizer installed"},
                                  self.auth_token)
                    else:
                        dump = bool(pickle.loads(body)) if body else False
                        with self._lock:
                            states = u.get_states(dump)
                        _send_msg(conn, {"ok": True, "value": states},
                                  self.auth_token)
                    continue
                elif head == "set_optimizer_states":
                    u = getattr(self, "_updater_obj", None)
                    if u is None:
                        _send_msg(conn, {"ok": False,
                                         "error": "no optimizer installed"},
                                  self.auth_token)
                    else:
                        with self._lock:
                            u.set_states(body)
                        _send_msg(conn, {"ok": True}, self.auth_token)
                    continue
                elif head == "stop":
                    self._stop.set()
                elif self.controller is not None and \
                        not head.startswith("profiler_"):
                    # user controller (parity: MXKVStoreRunServer's
                    # MXKVStoreServerController receives every
                    # application-defined command)
                    err = None
                    try:
                        self.controller(head, body)
                    except Exception as e:
                        err = str(e)
                    _send_msg(conn, {"ok": err is None, "error": err},
                              self.auth_token)
                    continue
                elif head.startswith("profiler_"):
                    # server-side profiling (parity: reference
                    # KVStoreServerProfilerCommand, include/mxnet/
                    # kvstore.h:49). Guarded: a profiler failure must
                    # not kill the PS connection — push/pull traffic
                    # outranks tracing.
                    err = None
                    try:
                        self._profiler_command(head, pickle.loads(body))
                    except Exception as e:  # reply, don't die
                        err = str(e)
                    _send_msg(conn, {"ok": err is None, "error": err},
                              self.auth_token)
                    continue
                _send_msg(conn, {"ok": True}, self.auth_token)
            else:
                _send_msg(conn, {"ok": False, "error": f"bad op {op}"}, self.auth_token)
        conn.close()


class KVClient:
    """Worker-side connection (parity: ps::KVWorker)."""

    def __init__(self, host, port, rank, num_workers, timeout=120,
                 heartbeat_interval=None):
        self.rank = rank
        self.num_workers = num_workers
        self._push_counts = {}    # key -> sync pushes sent (pull versioning)
        self._host, self._port = host, port
        self._timeout = timeout
        self.sock = self._connect(timeout)
        self._lock = threading.Lock()
        self._closed = False
        self._last_sent_bytes = 0  # wire size of the last sent batch
        # retry jitter stream: seeded by rank so a worker fleet's retry
        # storms decorrelate deterministically
        self._retry_rng = random.Random(1 + int(rank))
        # heartbeat loop announcing liveness (ps-lite van heartbeats) on
        # its OWN connection — a barrier or versioned pull can block the
        # main RPC socket for up to 100s and must not stall liveness.
        # interval 0 disables (some tests drive heartbeats manually)
        if heartbeat_interval is not None:
            self._hb_interval = float(heartbeat_interval)
        else:
            from .config import get as _cfg
            self._hb_interval = _cfg("MXNET_KVSTORE_HEARTBEAT_INTERVAL")
        self._hb_stop = threading.Event()
        self._hb_sock = None
        self._hb_lock = threading.Lock()
        if self._hb_interval > 0:
            self.heartbeat()
            t = threading.Thread(target=self._heartbeat_loop, daemon=True)
            t.start()

    def _connect(self, timeout):
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        deadline = time.time() + timeout
        while True:
            try:
                sock.connect((self._host, self._port))
                return sock
            except (ConnectionRefusedError, socket.timeout):
                if time.time() > deadline:
                    raise
                time.sleep(0.1)

    def _heartbeat_loop(self):
        import logging
        while not self._hb_stop.wait(self._hb_interval):
            try:
                self.heartbeat()
            except Exception as e:
                # connection gone; the owner will notice on its own RPCs
                logging.getLogger("mxnet_tpu.kvstore").debug(
                    "worker %d heartbeat loop exiting: %s", self.rank, e)
                return

    def heartbeat(self, step=None):
        msg = {"op": "heartbeat", "rank": self.rank}
        if step is not None:
            msg["step"] = int(step)
        with self._hb_lock:
            if self._hb_stop.is_set():
                # closed client must not transparently reconnect (it would
                # report itself alive and leak the socket)
                raise RuntimeError("heartbeat after close()")
            if self._hb_sock is None:
                self._hb_sock = self._connect(self._timeout)
            _send_msg(self._hb_sock, msg)
            resp = _recv_msg(self._hb_sock)
        if resp is None or not resp.get("ok"):
            raise RuntimeError("heartbeat rpc failed")

    def num_dead_node(self, timeout=60):
        return int(self._rpc({"op": "num_dead_node",
                              "timeout": timeout})["value"])

    def peer_states(self):
        """{rank: {"state": alive|lost|unknown, "age_s", "step"}} from
        the server's liveness layer (one bounded RPC round trip)."""
        raw = self._rpc({"op": "peer_states"})["value"]
        return {int(r): v for r, v in raw.items()}

    def report_progress(self, step):
        """Publish this rank's training progress (window-boundary step
        counter) so supervisors can measure recovery wall time."""
        self._rpc({"op": "progress", "rank": self.rank,
                   "step": int(step)})

    def push_telemetry(self, payload):
        """Push this rank's registry snapshot (full or delta-encoded)
        for the leader's fleet merge (telemetry.fleet; payload must be
        pickle/JSON-native).  Returns the server reply — ``acked`` (the
        committed delta baseline) or ``resync`` (baseline forgotten:
        the reporter answers with one full push)."""
        return self._rpc({"op": "telemetry_push", "rank": self.rank,
                          "payload": payload})

    def last_sent_bytes(self):
        """Wire bytes (length header + MAC + pickled body) of the most
        recent successfully-sent RPC batch on this client — the fleet
        reporter's push accounting reads this instead of re-pickling
        its payload."""
        with self._lock:
            return self._last_sent_bytes

    def fleet_state(self):
        """The server's merged fleet snapshot (one bounded RPC)."""
        return self._rpc({"op": "fleet"})["value"]

    def barrier_deadline(self, deadline_s):
        """A barrier whose server-side wait is bounded by an explicit
        deadline; fails typed (PeerLostError) when a participating rank
        is lost instead of waiting the deadline out."""
        self._rpc({"op": "barrier", "deadline": float(deadline_s)})

    def close(self):
        self._closed = True  # retry loops must not resurrect the socket
        self._hb_stop.set()
        # close sockets so the server-side handler threads unblock. The
        # heartbeat socket stays SET (not None) so a racing heartbeat()
        # fails on the dead fd instead of transparently reconnecting
        # post-close; the loop treats that failure as its stop signal.
        with self._hb_lock:
            if self._hb_sock is not None:
                try:
                    self._hb_sock.close()
                except OSError:
                    pass
        # shutdown OUTSIDE self._lock: an in-flight RPC (e.g. a barrier
        # blocked in recv for up to 120s) holds the lock — shutdown aborts
        # that recv immediately instead of waiting it out.  _closed (set
        # above) keeps the retry loop from reconnecting the aborted RPC.
        try:
            # graftlint: disable=lock-discipline -- deliberate bare read: aborting the in-flight recv is the point, and _closed fences the retry path
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            # graftlint: disable=lock-discipline -- same deliberate bare read as the shutdown above
            self.sock.close()
        except OSError:
            pass

    def _rpc(self, msg):
        return self._rpc_many([msg])[0]

    def _attempt(self, msgs):
        """One locked send-all + drain-all pass.  Transport failures
        (socket errors, timeouts, a peer close mid-reply) surface as
        OSError/ConnectionError for the retry loop in :meth:`_rpc_many`;
        protocol failures (bad MAC, oversized frame) stay RuntimeError
        and are never retried."""
        with self._lock:
            sent = 0
            for m in msgs:
                _failpoint("kvstore/client/rpc")
                sent += _send_msg(self.sock, m)
            self._last_sent_bytes = sent
            resps = [_recv_msg(self.sock) for _ in msgs]
        if any(r is None for r in resps):
            raise ConnectionError("kvstore server closed the connection")
        return resps

    def _rpc_many(self, msgs):
        """Pipelined round-trips with bounded retry (one lock hold, one
        in-flight window — big-array chunking doesn't serialize latency).

        Transport failures reconnect and resend with exponential backoff
        + seeded jitter, at most ``MXNET_KVSTORE_RETRIES`` extra
        attempts, then raise — bounded, never a silent hang (ISSUE 8).
        Caveat: a reply lost AFTER the server processed a sync push is
        retried as at-least-once; the deterministic chaos scenarios
        inject before the send, where the retry is exact.
        """
        from .config import get as _cfg
        retries = max(0, int(_cfg("MXNET_KVSTORE_RETRIES")))
        base = float(_cfg("MXNET_KVSTORE_RETRY_BACKOFF_S"))
        attempt = 0
        while True:
            try:
                resps = self._attempt(msgs)
                break
            except (OSError, ConnectionError) as e:
                if self._closed:
                    raise RuntimeError(
                        "kvstore client is closed") from e
                if attempt >= retries:
                    raise RuntimeError(
                        f"kvstore server rpc failed after {attempt + 1} "
                        f"attempt(s): {type(e).__name__}: {e}") from e
                delay = base * (2 ** attempt) * \
                    (1.0 + self._retry_rng.random())
                logging.getLogger("mxnet_tpu.kvstore").warning(
                    "worker %d: rpc transport failure (%s: %s); retry "
                    "%d/%d in %.0f ms", self.rank, type(e).__name__, e,
                    attempt + 1, retries, delay * 1e3)
                time.sleep(delay)
                with self._lock:
                    try:
                        self.sock.close()
                    except OSError:
                        pass
                    self.sock = self._connect(self._timeout)
                attempt += 1
        for resp in resps:
            if not resp.get("ok"):
                if resp.get("error_type") == "PeerLostError":
                    # protocol-level typed failure: a rank this RPC was
                    # waiting on is dead.  Never retried (retrying
                    # cannot resurrect the peer) — the elastic recovery
                    # path owns what happens next.
                    raise PeerLostError(resp.get("dead_ranks", ()),
                                        resp.get("error", ""))
                raise RuntimeError(f"kvstore server rpc failed: {resp}")
        return resps

    def init(self, key, value):
        self._rpc({"op": "init", "key": key, "value": np.asarray(value)})

    def push(self, key, value, sync=True):
        self._rpc({"op": "push", "key": key, "value": np.asarray(value),
                   "sync": sync})
        if sync:
            # _push_counts is owner-thread state: the spawned heartbeat
            # thread only ever touches _hb_* attributes
            # graftlint: disable=lock-discipline -- single-owner-thread state
            self._push_counts[key] = self._push_counts.get(key, 0) + 1

    def push_compressed(self, key, encoded, sync=True):
        """Push a 2-bit-compressed gradient (dict from
        GradientCompression.encode_push)."""
        self._rpc({"op": "push", "key": key, "value": encoded,
                   "sync": sync})
        if sync:
            self._push_counts[key] = self._push_counts.get(key, 0) + 1

    def push_rs(self, key, indices, values, shape, sync=True):
        """Push a row_sparse value: only (indices, values) cross the wire."""
        self._rpc({"op": "push", "key": key,
                   "value": {"indices": np.asarray(indices),
                             "values": np.asarray(values),
                             "shape": tuple(shape)},
                   "sync": sync})
        if sync:
            self._push_counts[key] = self._push_counts.get(key, 0) + 1

    def pull(self, key):
        return self._rpc({"op": "pull", "key": key,
                          "min_version": self._push_counts.get(key, 0)}
                         )["value"]

    def init_many(self, kv_pairs):
        self._rpc_many([{"op": "init", "key": k, "value": np.asarray(v)}
                        for k, v in kv_pairs])

    def push_many(self, kv_pairs, sync=True):
        self._rpc_many([{"op": "push", "key": k, "value": np.asarray(v),
                         "sync": sync} for k, v in kv_pairs])
        if sync:
            for k, _v in kv_pairs:
                self._push_counts[k] = self._push_counts.get(k, 0) + 1

    def pull_many(self, keys):
        resps = self._rpc_many(
            [{"op": "pull", "key": k,
              "min_version": self._push_counts.get(k, 0)} for k in keys])
        return [r["value"] for r in resps]

    def pull_rows(self, key, rows):
        """Pull only the requested rows (row_sparse pull)."""
        return self._rpc({"op": "pull", "key": key,
                          "rows": np.asarray(rows),
                          "min_version": self._push_counts.get(key, 0)}
                         )["value"]

    def barrier(self):
        self._rpc({"op": "barrier"})

    def send_command(self, head, body):
        self._rpc({"op": "command", "head": head, "body": body})

    def command(self, head, body):
        """A server command whose REPLY matters (e.g.
        get_optimizer_states returns {"value": bytes})."""
        return self._rpc({"op": "command", "head": head, "body": body})

    def stop_server(self):
        self._rpc({"op": "command", "head": "stop", "body": b""})


def run_server_from_env():
    """Entry for DMLC_ROLE=server processes (parity:
    python/mxnet/kvstore_server.py _init_kvstore_server_module)."""
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", 9091))
    num_workers = int(os.environ.get("DMLC_NUM_WORKER", 1))
    server = KVServer(port=port, num_workers=num_workers)
    server.run()


if __name__ == "__main__":
    run_server_from_env()
