"""Global random state.

Reference keeps per-context mshadow PRNGs seeded via MXRandomSeed
(src/resource.cc kRandom). TPU redesign: a single counter-based root key;
every random op folds in a fresh counter, so seeding is reproducible and
device-count independent.
"""
from __future__ import annotations

import threading

import jax

_state = threading.local()


def _get():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
        _state.counter = 0
    return _state


def seed(seed_state, ctx=None):
    """mx.random.seed(n) parity (ctx arg accepted and ignored: keys are
    device-independent)."""
    s = _get()
    s.key = jax.random.PRNGKey(int(seed_state))
    s.counter = 0


def next_key():
    s = _get()
    s.counter += 1
    trace_key = getattr(_state, "trace_key", None)
    if trace_key is not None:
        # under CachedOp/jit tracing: derive from the traced per-call key so
        # every compiled invocation gets fresh randomness (a concrete key
        # would bake one dropout mask into the executable)
        return jax.random.fold_in(trace_key, s.counter)
    return jax.random.fold_in(s.key, s.counter)


class trace_key_scope:
    """Context manager installing a traced base key for random ops."""

    def __init__(self, key):
        self._key = key
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_state, "trace_key", None)
        _state.trace_key = self._key
        return self

    def __exit__(self, *a):
        _state.trace_key = self._prev


def current_key():
    return _get().key
