"""mx.sym.image — symbolic namespace over the `_image_*` operator family
(reference: python/mxnet/symbol/image.py)."""
from __future__ import annotations

from ..ndarray.image import _IMAGE_OPS
from ..ops import registry as _registry


def __getattr__(name):
    op_name = _IMAGE_OPS.get(name)
    if op_name is not None:
        from . import _make_sym_func
        fn = _make_sym_func(_registry.get(op_name))
        globals()[name] = fn
        return fn
    raise AttributeError(
        f"module 'mxnet_tpu.symbol.image' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_IMAGE_OPS))
