"""Executor: run a bound symbolic graph.

Re-design of reference src/executor/graph_executor.cc (Executor::Bind:1906,
SimpleBind:1874, Forward:66, Backward:79). The reference builds the full
fwd+bwd graph, plans memory (plan_memory.cc), attaches one engine op per node
and bulks segments. Here the entire graph is traced once into a single jitted
XLA computation per input signature (forward) and a jitted vjp pair
(backward) — XLA does memory planning/fusion/scheduling. Aux states
(BatchNorm moving stats) are extra traced outputs written back after each
forward, matching the reference's in-place aux mutation semantics.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .. import random as _random
from ..base import MXNetError
from ..context import current_context
from ..ndarray import NDArray
from ..ops import registry as _registry

_BWD_EXEC = jax.jit(lambda vjp_fn, cts: vjp_fn(cts))


class Executor:
    """Executor for a Symbol (parity: python/mxnet/executor.py Executor)."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx or current_context()
        # group2ctx model parallelism: only engage the multi-device path
        # when the graph actually carries ctx_group annotations
        self._grouped = None
        self._group2ctx = group2ctx
        if group2ctx:
            has_groups = any(n.attrs.get("ctx_group")
                             for n in symbol._topo())
            if has_groups:
                from .grouped import GroupedRunner
                self._grouped = GroupedRunner(symbol, group2ctx, self._ctx)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        if isinstance(args, dict):
            self.arg_dict = dict(args)
        else:
            if len(args) != len(arg_names):
                raise MXNetError(
                    f"bind: expected {len(arg_names)} args "
                    f"({arg_names}), got {len(args)}")
            self.arg_dict = dict(zip(arg_names, args))
        self.arg_arrays = [self.arg_dict.get(n) for n in arg_names]

        if isinstance(aux_states, dict):
            self.aux_dict = dict(aux_states)
        elif aux_states is None:
            self.aux_dict = {}
        else:
            self.aux_dict = dict(zip(aux_names, aux_states))
        self.aux_arrays = [self.aux_dict.get(n) for n in aux_names]

        if isinstance(args_grad, dict):
            self.grad_dict = dict(args_grad)
        elif args_grad is None:
            self.grad_dict = {}
        else:
            self.grad_dict = dict(zip(arg_names, args_grad))

        if isinstance(grad_req, str):
            self.grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self.grad_req = dict(zip(arg_names, grad_req))
        else:
            self.grad_req = dict(grad_req)

        self._arg_names = arg_names
        self._aux_names = aux_names
        self.outputs = []
        self._monitor_callback = None
        self._monitor_all = False
        self._fn_cache = {}
        self._vjp_holder = None
        self._last_is_train = False

    # -- graph compilation -------------------------------------------------
    def _build_fn(self, is_train):
        """Trace the graph into fn(key, arg_arrays, aux_arrays) ->
        (outputs, new_aux_arrays)."""
        sym = self._symbol
        topo = sym._topo()
        arg_names = self._arg_names
        aux_names = self._aux_names

        def fn(key, arg_arrays, aux_arrays):
            env = {}
            arg_map = dict(zip(arg_names, arg_arrays))
            aux_map = dict(zip(aux_names, aux_arrays))
            new_aux = dict(aux_map)
            counter = 0
            for node in topo:
                if node.is_variable():
                    if node.name in arg_map:
                        env[(node, 0)] = arg_map[node.name]
                    elif node.name in aux_map:
                        env[(node, 0)] = aux_map[node.name]
                    else:
                        raise MXNetError(
                            f"executor: variable {node.name} was not bound")
                    continue
                op = _registry.get(node.op)
                ins = [env[e] for e in node.inputs]
                attrs = {k: v for k, v in node.attrs.items()
                         if not k.startswith("__")}
                from ..ndarray.ndarray import _TRAINING_ATTR_OPS
                if op.name in _TRAINING_ATTR_OPS:
                    attrs["_training"] = is_train
                if op.is_random:
                    counter += 1
                    ins = [jax.random.fold_in(key, counter)] + ins
                out = op.grad_aware(attrs)(*ins)
                outs = out if isinstance(out, (tuple, list)) else (out,)
                mutate_aux = op.resolve_mutate_aux(attrs)
                n_user = len(outs) - len(mutate_aux)
                for i, o in enumerate(outs[:n_user]):
                    env[(node, i)] = o
                # route mutated aux outputs back to their aux variables
                for j, in_idx in enumerate(mutate_aux):
                    src_node, _ = node.inputs[in_idx]
                    if src_node.is_variable() and src_node.name in new_aux:
                        new_aux[src_node.name] = outs[n_user + j]
            outputs = tuple(env[e] for e in sym._outputs)
            return outputs, tuple(new_aux[n] for n in aux_names)

        return fn

    def _get_jitted(self, is_train):
        key = (is_train,
               tuple((a.shape, str(a.dtype)) if a is not None else None
                     for a in self.arg_arrays),
               tuple((a.shape, str(a.dtype)) if a is not None else None
                     for a in self.aux_arrays))
        entry = self._fn_cache.get(key)
        if entry is None:
            # every framework jit build is a TraceLedger event (ISSUE 7
            # retrace ratchet) — cold path only, one dict write
            from .. import compile as _compile
            _compile.record_trace("executor",
                                  "train" if is_train else "infer")
            fn = self._build_fn(is_train)
            jitted = jax.jit(fn)
            grad_args = [i for i, n in enumerate(self._arg_names)
                         if self.grad_req.get(n, "null") != "null"]

            def fwd_vjp(key_arr, arg_arrays, aux_arrays):
                ga = [arg_arrays[i] for i in grad_args]

                def f(*diff):
                    full = list(arg_arrays)
                    for i, d in zip(grad_args, diff):
                        full[i] = d
                    outs, new_aux = fn(key_arr, tuple(full), aux_arrays)
                    return outs, new_aux

                return jax.vjp(f, *ga)

            fwd_vjp_jit = jax.jit(fwd_vjp)
            entry = (jitted, fwd_vjp_jit, grad_args)
            self._fn_cache[key] = entry
        return entry

    # -- execution ---------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Run forward (parity: executor.py forward → GraphExecutor::Forward)."""
        from .. import ndarray as nd
        if kwargs:
            for name, val in kwargs.items():
                if name not in self.arg_dict:
                    raise MXNetError(f"unknown argument {name}")
                if isinstance(val, NDArray):
                    self.arg_dict[name][:] = val
                else:
                    self.arg_dict[name][:] = nd.array(val)
        if self._grouped is not None:
            return self._forward_grouped(bool(is_train))
        jitted, fwd_vjp_jit, grad_args = self._get_jitted(bool(is_train))
        key_arr = _random.next_key()
        arg_arrays = tuple(a._data for a in self.arg_arrays)
        aux_arrays = tuple(a._data for a in self.aux_arrays)
        if is_train and grad_args:
            (outs, new_aux), vjp_fn = fwd_vjp_jit(key_arr, arg_arrays,
                                                  aux_arrays)
            self._vjp_holder = (vjp_fn, grad_args,
                                [jnp.zeros_like(a) for a in new_aux])
        else:
            outs, new_aux = jitted(key_arr, arg_arrays, aux_arrays)
            self._vjp_holder = None
        from .. import profiler as _prof
        _prof.record_dispatch("graph")
        self._last_is_train = bool(is_train)
        for arr, new in zip(self.aux_arrays, new_aux):
            arr._set_data(new)
        self.outputs = [NDArray(o, self._ctx) for o in outs]
        if self._monitor_callback is not None:
            self._run_monitor()
        return self.outputs

    def _forward_grouped(self, is_train):
        """Multi-device forward via GroupedRunner (group2ctx path)."""
        key_arr = _random.next_key()
        want_tape = is_train and any(
            self.grad_req.get(n, "null") != "null" for n in self._arg_names)
        arg_map = {n: a._data for n, a in zip(self._arg_names,
                                              self.arg_arrays)
                   if a is not None}
        aux_map = {n: a._data for n, a in zip(self._aux_names,
                                              self.aux_arrays)
                   if a is not None}
        outs, new_aux, tape = self._grouped.run(
            key_arr, arg_map, aux_map, is_train, want_tape)
        self._grouped_tape = tape
        self._vjp_holder = None
        self._last_is_train = is_train
        for name, arr in zip(self._aux_names, self.aux_arrays):
            if arr is not None:
                arr._set_data(new_aux[name])
        self.outputs = [NDArray(o, self._ctx) for o in outs]
        if self._monitor_callback is not None:
            self._run_monitor()
        return self.outputs

    def _backward_grouped(self, out_grads):
        if getattr(self, "_grouped_tape", None) is None:
            raise MXNetError(
                "backward requires forward(is_train=True) first")
        if out_grads is None:
            cts = [jnp.ones_like(o._data) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = [g._data for g in out_grads]
        grads_by_entry = {}
        for entry, g in zip(self._symbol._outputs, cts):
            prev = grads_by_entry.get(entry)
            # duplicate output entries (Group([y, y])) sum their cotangents,
            # matching the single-jit vjp path
            grads_by_entry[entry] = g if prev is None else prev + g
        var_grads = self._grouped.backward(self._grouped_tape,
                                           grads_by_entry)
        for name, g in var_grads.items():
            req = self.grad_req.get(name, "null")
            tgt = self.grad_dict.get(name)
            if tgt is None or req == "null":
                continue
            if req == "add":
                tgt._set_data(tgt._data + jax.device_put(
                    g, next(iter(tgt._data.devices()))))
            else:
                tgt._set_data(jax.device_put(
                    g, next(iter(tgt._data.devices()))).astype(tgt.dtype))

    def backward(self, out_grads=None, is_train=True):
        """Run backward and accumulate into args_grad per grad_req
        (parity: executor.py backward → GraphExecutor::Backward)."""
        if self._grouped is not None:
            return self._backward_grouped(out_grads)
        if self._vjp_holder is None:
            raise MXNetError(
                "backward requires forward(is_train=True) first (parity: "
                "reference requires bind with args_grad + train forward)")
        vjp_fn, grad_args, zero_aux = self._vjp_holder
        if out_grads is None:
            cts = tuple(jnp.ones_like(o._data) for o in self.outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            cts = tuple(g._data for g in out_grads)
        grads = _BWD_EXEC(vjp_fn, (cts, tuple(zero_aux)))
        from .. import profiler as _prof
        _prof.record_dispatch("graph")
        for i, g in zip(grad_args, grads):
            name = self._arg_names[i]
            req = self.grad_req.get(name, "null")
            tgt = self.grad_dict.get(name)
            if tgt is None or req == "null":
                continue
            if req == "add":
                tgt._set_data(tgt._data + g)
            else:
                tgt._set_data(g.astype(tgt.dtype))

    # -- utility -----------------------------------------------------------
    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """Copy parameter values (parity: executor.py copy_params_from)."""
        for name, array in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name][:] = array
            elif not allow_extra_params:
                raise MXNetError(f"Found name \"{name}\" that is not in the "
                                 "arguments")
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name][:] = array
                elif not allow_extra_params:
                    raise MXNetError(f"Found name \"{name}\" that is not in "
                                     "the auxiliary states")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes (parity:
        executor.py reshape; cheap here — recompile happens lazily).

        partial_shaping: permit args NOT named in kwargs to change shape
        (else that's an error, the reference contract). allow_up_sizing:
        permit new shapes with more elements than the old array (the
        reference refuses to grow buffers silently without it)."""
        from .. import ndarray as nd
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for name, shape in zip(self._arg_names, arg_shapes):
            old = self.arg_dict.get(name)
            if old is not None and tuple(old.shape) == tuple(shape):
                new_args[name] = old
            else:
                if old is not None:
                    if not partial_shaping and name not in kwargs:
                        raise MXNetError(
                            f"reshape changes the shape of {name!r} "
                            f"({tuple(old.shape)} -> {tuple(shape)}) which "
                            "was not listed; pass partial_shaping=True "
                            "to allow it (reference MXExecutorReshapeEx "
                            "contract)")
                    if (not allow_up_sizing
                            and int(np.prod(shape))
                            > int(np.prod(old.shape))):
                        raise MXNetError(
                            f"reshape grows {name!r} from "
                            f"{tuple(old.shape)} to {tuple(shape)}; pass "
                            "allow_up_sizing=True to permit buffer "
                            "growth")
                new_args[name] = nd.zeros(shape, ctx=self._ctx,
                                          dtype=old.dtype if old is not None
                                          else np.float32)
        new_grads = None
        if self.grad_dict:
            new_grads = {}
            for name, arr in self.grad_dict.items():
                shape = new_args[name].shape
                if tuple(arr.shape) == tuple(shape):
                    # unchanged shape: SHARE the grad array so grad_req
                    # 'add' accumulation survives a reshape (reference
                    # reshape shares untouched buffers)
                    new_grads[name] = arr
                else:
                    new_grads[name] = nd.zeros(shape, ctx=self._ctx,
                                               dtype=arr.dtype)
        new_aux = {}
        for name, shape in zip(self._aux_names, aux_shapes):
            old = self.aux_dict.get(name)
            if old is not None and tuple(old.shape) == tuple(shape):
                new_aux[name] = old
            else:
                new_aux[name] = nd.zeros(shape, ctx=self._ctx)
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self.grad_req, new_aux,
                        group2ctx=self._group2ctx)

    def _run_monitor(self):
        if self._monitor_all:
            # inputs first (monitor_all contract: inputs AND outputs)
            for n, a in zip(self._arg_names, self.arg_arrays):
                if a is not None:
                    self._monitor_callback(n, a)
            for n, a in zip(self._aux_names, self.aux_arrays):
                if a is not None:
                    self._monitor_callback(n, a)
        for n, o in zip(self._symbol.list_outputs(), self.outputs):
            self._monitor_callback(n, o)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install the monitor callback (parity: graph_executor.cc:1403
        monitor_callback_).

        monitor_all=False reports the graph outputs after each forward;
        monitor_all=True additionally reports the bound inputs (arg and
        aux arrays).  Per-internal-node values are not observable here —
        the whole graph is ONE fused XLA program (use
        Symbol.get_internals() to bind an executor that exposes them,
        the documented TPU-era equivalent)."""
        self._monitor_callback = callback
        self._monitor_all = bool(monitor_all)

    def debug_str(self):
        lines = ["Symbol Outputs:"]
        for n in self._symbol.list_outputs():
            lines.append(f"\toutput[{n}]")
        for node in self._symbol._topo():
            if not node.is_variable():
                lines.append(f"Op:{node.op}, Name={node.name}")
        return "\n".join(lines)
