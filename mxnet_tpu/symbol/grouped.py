"""Context-group model parallelism (parity: group2ctx).

Reference: Executor::SimpleBind's group2ctx map + AssignContext pass
(src/executor/graph_executor.cc:985,1876) place annotated subgraphs on
different devices and the engine inserts cross-device copies
(src/operator/cross_device_copy.cc). The TPU re-design: nodes annotated
``ctx_group`` (via AttrScope or var attr) are executed
computation-follows-data — each op's inputs are device_put onto the
group's device and the op runs there; JAX's async dispatch overlaps the
per-device streams exactly like the reference engine's per-device worker
queues.

Backward is a per-node vjp tape recorded during forward (the whole-graph
single-jit path in executor.py cannot express multi-device placement:
XLA pins one device per computation). Aux-state updates (BatchNorm
moving stats) are primal side-outputs, excluded from differentiation —
same contract as the single-jit path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ops import registry as _registry


def _as_tuple(x):
    return tuple(x) if isinstance(x, (tuple, list)) else (x,)


class GroupedRunner:
    """Execute a Symbol graph with per-group device placement."""

    def __init__(self, symbol, group2ctx, default_ctx):
        self._symbol = symbol
        self._default_dev = default_ctx.jax_device
        self._group_dev = {}
        for group, ctx in (group2ctx or {}).items():
            self._group_dev[group] = ctx.jax_device

    def _node_device(self, node):
        group = node.attrs.get("ctx_group")
        # reference semantics: unmapped groups fall back to the default ctx
        return self._group_dev.get(group, self._default_dev)

    def run(self, key, arg_map, aux_map, is_train, want_tape):
        """Forward pass. Returns (outputs, new_aux, tape).

        arg_map/aux_map: name -> jax array. When ``want_tape`` each op is
        run under jax.vjp and the tape records
        (node, input_entries, vjp_fn, out_avals, is_random).
        """
        sym = self._symbol
        env = {}
        new_aux = dict(aux_map)
        tape = [] if want_tape else None
        counter = 0
        for node in sym._topo():
            if node.is_variable():
                dev = self._node_device(node)
                if node.name in arg_map:
                    val = arg_map[node.name]
                elif node.name in aux_map:
                    val = aux_map[node.name]
                else:
                    raise MXNetError(
                        f"executor: variable {node.name} was not bound")
                env[(node, 0)] = jax.device_put(val, dev)
                continue
            op = _registry.get(node.op)
            dev = self._node_device(node)
            ins = [jax.device_put(env[e], dev) for e in node.inputs]
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__") and k != "ctx_group"}
            from ..ndarray.ndarray import _TRAINING_ATTR_OPS
            if op.name in _TRAINING_ATTR_OPS:
                attrs["_training"] = is_train
            if op.is_random:
                counter += 1
                ins = [jax.device_put(jax.random.fold_in(key, counter),
                                      dev)] + ins
            raw = op.grad_aware(attrs)
            if want_tape:
                outs, vjp_fn = jax.vjp(lambda *a: _as_tuple(raw(*a)), *ins)
                tape.append((node, list(node.inputs), vjp_fn,
                             [(o.shape, o.dtype) for o in outs],
                             op.is_random, dev))
            else:
                outs = _as_tuple(raw(*ins))
            mutate_aux = op.resolve_mutate_aux(node.attrs)
            n_user = len(outs) - len(mutate_aux)
            for i, o in enumerate(outs[:n_user]):
                env[(node, i)] = o
            for j, in_idx in enumerate(mutate_aux):
                src_node, _ = node.inputs[in_idx]
                if src_node.is_variable() and src_node.name in new_aux:
                    new_aux[src_node.name] = outs[n_user + j]
        outputs = tuple(env[e] for e in sym._outputs)
        return outputs, new_aux, tape

    def backward(self, tape, out_grads):
        """Walk the tape in reverse, accumulating per-variable cotangents.

        out_grads: {(node, out_idx): cotangent} for the symbol outputs.
        Returns {var_name: cotangent}.
        """
        sym = self._symbol
        cts = {}
        for entry, g in out_grads.items():
            _accum(cts, entry, g)
        for node, in_entries, vjp_fn, out_avals, is_random, dev \
                in reversed(tape):
            op = _registry.get(node.op)
            n_user = len(out_avals) - len(op.resolve_mutate_aux(node.attrs))
            have_any = any(cts.get((node, i)) is not None
                           for i in range(n_user))
            if not have_any:
                continue  # nothing downstream consumed this node
            out_ct = []
            for i, (shape, dtype) in enumerate(out_avals):
                g = cts.get((node, i)) if i < n_user else None
                # aux updates carry zero cotangent (not differentiated);
                # cotangents flow in from downstream devices — hop them
                # onto this node's device (the reverse cross-device copy
                # the reference engine would insert)
                out_ct.append(jax.device_put(
                    g if g is not None else jnp.zeros(shape, dtype), dev))
            in_cts = vjp_fn(tuple(out_ct))
            offset = 1 if is_random else 0  # skip RNG-key cotangent
            for e, g in zip(in_entries, in_cts[offset:]):
                _accum(cts, e, g)
        var_grads = {}
        for node in sym._topo():
            if node.is_variable() and (node, 0) in cts:
                var_grads[node.name] = cts[(node, 0)]
        return var_grads


def _accum(cts, entry, g):
    cur = cts.get(entry)
    if cur is None:
        cts[entry] = g
    else:
        # cross-device consumers: accumulate on the first consumer's device
        cts[entry] = cur + jax.device_put(g, next(iter(cur.devices())))
