"""mx.sym.contrib — symbolic contrib namespace (parity:
python/mxnet/symbol/contrib.py codegen over _contrib_* registrations +
the control-flow builders foreach/while_loop/cond)."""
from .control_flow import cond, foreach, while_loop  # noqa: F401


def __getattr__(name):
    from ..ops import registry as _registry
    from . import _make_sym_func
    if _registry.exists(f"_contrib_{name}"):
        fn = _make_sym_func(_registry.get(f"_contrib_{name}"))
        globals()[name] = fn  # cache: next access skips __getattr__
        return fn
    raise AttributeError(
        f"module 'mxnet_tpu.symbol.contrib' has no attribute {name!r}")
