"""Symbolic control flow: foreach / while_loop / cond as GRAPH NODES.

Parity: reference src/operator/control_flow.cc (`_foreach`:1089,
`_while_loop`:1150, `_cond`:1211) + python/mxnet/symbol/contrib.py
(foreach/while_loop/cond builders that cut the body into a subgraph).

TPU redesign: the body symbols serialize into the node's attrs as JSON
(the `_subgraph` pattern, subgraph.py); at execution the registered ops
re-trace them with subgraph.exec_subgraph and wrap the trace in the
matching lax combinator — `lax.scan` for foreach, scan+active-flag for
while_loop (differentiable, bounded — identical to the imperative
ndarray/contrib.py lowering), `lax.cond` for cond.  Sequence length
never unrolls into the graph: compile time is O(1) in T.

Body closures may reference outer VARIABLES (weights) freely — they
become loop-invariant node inputs; outer COMPUTED symbols are inlined
into the subgraph and hoisted by XLA's loop-invariant code motion.
"""
from __future__ import annotations

import json


from ..base import MXNetError
from ..ops import registry as _registry
from .symbol import Group, Symbol, _SymNode, var


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _cut(sub_sym, bound_names):
    """Split the subgraph's variables into (bound, free) preserving
    bound order; free vars keep their outer _SymNode objects so the
    caller can wire them as node inputs."""
    free_nodes = []
    seen = set()
    for node in sub_sym._topo():
        if node.is_variable() and node.name not in bound_names and \
                id(node) not in seen:
            seen.add(id(node))
            free_nodes.append(node)
    return free_nodes


_UID = [0]


def _gensym(kind):
    """Unique bound-variable prefix per builder call: fixed names would
    let an INNER nested loop's _cut absorb an outer loop's bound
    variable by name collision and silently rebind it (caught in
    review; reference contrib.py gets uniqueness from the NameManager).
    """
    _UID[0] += 1
    return f"__{kind}{_UID[0]}"


def _flatten(syms):
    """Flatten possibly multi-output symbols into single-output ones so
    output COUNTS match the serialized subgraph's outputs (Group
    flattens; reference contrib.py counts via list_outputs)."""
    out = []
    for s in syms:
        out.extend(list(s))
    return out


def _mk_node(op_name, entries, attrs, name, n_out):
    node = _SymNode(op_name, name, attrs, entries)
    return Symbol([(node, i) for i in range(n_out)])


def foreach(body, data, init_states, name="foreach"):
    """Scan ``body`` over axis 0 of ``data`` symbolically (parity:
    symbol/contrib.py foreach). body(data_slice, states) ->
    (outs, new_states). Returns (stacked_outs, final_states)."""
    data_l = _as_list(data)
    states_l = _as_list(init_states)
    uid = _gensym(name)
    slice_vars = [var(f"{uid}_slice{i}__") for i in range(len(data_l))]
    state_vars = [var(f"{uid}_state{i}__") for i in range(len(states_l))]
    d_arg = slice_vars if isinstance(data, (list, tuple)) else slice_vars[0]
    s_arg = state_vars if isinstance(init_states, (list, tuple)) \
        else state_vars[0]
    out, new_states = body(d_arg, s_arg)
    outs_l = _flatten(_as_list(out))
    ns_l = _as_list(new_states)
    if len(ns_l) != len(states_l):
        raise MXNetError(
            f"foreach body returned {len(ns_l)} states for "
            f"{len(states_l)} init_states")
    if any(len(s_._outputs) != 1 for s_ in ns_l):
        raise MXNetError("foreach states must be single-output symbols")
    sub = Group([*outs_l, *ns_l])
    bound = [v.name for v in slice_vars] + [v.name for v in state_vars]
    free_nodes = _cut(sub, set(bound))
    attrs = {
        "subgraph_json": sub.tojson(),
        "in_names": json.dumps(bound + [n.name for n in free_nodes]),
        "num_data": len(data_l),
        "num_states": len(states_l),
        "num_out_data": len(outs_l),
        "num_outputs": len(outs_l) + len(states_l),
    }
    entries = [s._outputs[0] for s in data_l] \
        + [s._outputs[0] for s in states_l] \
        + [(n, 0) for n in free_nodes]
    res = _mk_node("_foreach", entries, attrs, name,
                   len(outs_l) + len(states_l))
    outs = [res[i] for i in range(len(outs_l))]
    fin = [res[len(outs_l) + i] for i in range(len(states_l))]
    if isinstance(out, (list, tuple)):
        outs_r = outs
    elif len(outs) == 1:
        outs_r = outs[0]
    else:  # single MULTI-OUTPUT body symbol: keep every output reachable
        outs_r = Symbol([o._outputs[0] for o in outs])
    fin_r = fin if isinstance(init_states, (list, tuple)) else fin[0]
    return outs_r, fin_r


def while_loop(cond, func, loop_vars, max_iterations=None,
               name="while_loop"):
    """Bounded symbolic while loop (parity: symbol/contrib.py
    while_loop). cond(loop_vars)->scalar, func(loop_vars)->
    (step_outputs, new_loop_vars). Stacked outputs have axis 0 ==
    max_iterations (steps after termination are zero), like the
    imperative lowering."""
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations (bounded "
                         "loops are what compile to one XLA While)")
    lv_l = _as_list(loop_vars)
    uid = _gensym(name)
    lv_vars = [var(f"{uid}_var{i}__") for i in range(len(lv_l))]
    lv_arg = lv_vars if isinstance(loop_vars, (list, tuple)) else lv_vars[0]
    pred = cond(lv_arg)
    out, new_lv = func(lv_arg)
    outs_l = _flatten(_as_list(out))
    nlv_l = _as_list(new_lv)
    if len(nlv_l) != len(lv_l):
        raise MXNetError("while_loop func must return as many loop_vars "
                         "as it received")
    if any(len(s_._outputs) != 1 for s_ in nlv_l):
        raise MXNetError("while_loop loop_vars must be single-output "
                         "symbols")
    sub = Group([pred, *outs_l, *nlv_l])
    bound = [v.name for v in lv_vars]
    free_nodes = _cut(sub, set(bound))
    attrs = {
        "subgraph_json": sub.tojson(),
        "in_names": json.dumps(bound + [n.name for n in free_nodes]),
        "num_vars": len(lv_l),
        "num_out_data": len(outs_l),
        "max_iterations": int(max_iterations),
        "num_outputs": len(outs_l) + len(lv_l),
    }
    entries = [s._outputs[0] for s in lv_l] + [(n, 0) for n in free_nodes]
    res = _mk_node("_while_loop", entries, attrs, name,
                   len(outs_l) + len(lv_l))
    outs = [res[i] for i in range(len(outs_l))]
    fin = [res[len(outs_l) + i] for i in range(len(lv_l))]
    if isinstance(out, (list, tuple)):
        outs_r = outs
    elif len(outs) == 1:
        outs_r = outs[0]
    else:
        outs_r = Symbol([o._outputs[0] for o in outs])
    fin_r = fin if isinstance(loop_vars, (list, tuple)) else fin[0]
    return outs_r, fin_r


def cond(pred, then_func, else_func, name="cond"):
    """Symbolic if/else (parity: symbol/contrib.py cond). ``pred`` is a
    scalar Symbol; then_func/else_func are nullary closures over outer
    symbols returning outputs of matching structure."""
    then_out = _flatten(_as_list(then_func()))
    else_out = _flatten(_as_list(else_func()))
    if len(then_out) != len(else_out):
        raise MXNetError("cond branches must return the same number of "
                         "outputs")
    n_out = len(then_out)
    then_sub = Group(then_out) if n_out > 1 else then_out[0]
    else_sub = Group(else_out) if n_out > 1 else else_out[0]
    then_free = _cut(then_sub, set())
    else_free = _cut(else_sub, set())
    # union of branch inputs, stable order
    free_nodes, seen = [], set()
    for node in then_free + else_free:
        if id(node) not in seen:
            seen.add(id(node))
            free_nodes.append(node)
    attrs = {
        "then_json": then_sub.tojson(),
        "else_json": else_sub.tojson(),
        "in_names": json.dumps([n.name for n in free_nodes]),
        "num_outputs": n_out,
    }
    entries = [pred._outputs[0]] + [(n, 0) for n in free_nodes]
    res = _mk_node("_cond", entries, attrs, name, n_out)
    return res if n_out > 1 else res[0]


# --- registered ops ---------------------------------------------------------
def _names(v):
    """in_names is stored as a json string; the generic symbol-attr
    parser may pre-split it into a sequence of still-quoted elements —
    accept both forms."""
    if isinstance(v, str):
        return json.loads(v)
    out = []
    for x in v:
        x = str(x).strip()
        if len(x) >= 2 and x[0] in "\"'" and x[-1] == x[0]:
            x = x[1:-1]
        out.append(x)
    return out


def _inner(json_str):
    from ..subgraph import _inner_symbol
    return _inner_symbol(json_str)


def _foreach_fcompute(attrs, *arrays):
    import jax
    from ..subgraph import exec_subgraph
    sym = _inner(attrs["subgraph_json"])
    in_names = _names(attrs["in_names"])
    n_data = int(attrs["num_data"])
    n_states = int(attrs["num_states"])
    n_outs = int(attrs["num_out_data"])
    data_arrs = arrays[:n_data]
    states = arrays[n_data:n_data + n_states]
    frees = arrays[n_data + n_states:]

    def step(carry, xs):
        vals = dict(zip(in_names, list(xs) + list(carry) + list(frees)))
        outs = exec_subgraph(sym, vals, all_outputs=True)
        return tuple(outs[n_outs:]), tuple(outs[:n_outs])

    final, stacked = jax.lax.scan(step, tuple(states), tuple(data_arrs))
    return tuple(stacked) + tuple(final)


def _while_loop_fcompute(attrs, *arrays):
    import jax
    import jax.numpy as jnp
    from ..subgraph import exec_subgraph
    sym = _inner(attrs["subgraph_json"])
    in_names = _names(attrs["in_names"])
    n_vars = int(attrs["num_vars"])
    n_outs = int(attrs["num_out_data"])
    max_iter = int(attrs["max_iterations"])
    lvs = arrays[:n_vars]
    frees = arrays[n_vars:]

    def run(vals):
        outs = exec_subgraph(sym, vals, all_outputs=True)
        return outs[0], outs[1:1 + n_outs], outs[1 + n_outs:]

    # probe shapes once (abstractly traced by the caller's jit anyway)
    def step(carry, _):
        active, lv = carry
        vals = dict(zip(in_names, list(lv) + list(frees)))
        pred, step_outs, new_lv = run(vals)
        take = jnp.logical_and(active, pred.astype(bool).reshape(()))
        lv2 = tuple(jnp.where(take, n, o) for n, o in zip(new_lv, lv))
        outs = tuple(jnp.where(take, o, jnp.zeros_like(o))
                     for o in step_outs)
        return (take, lv2), outs

    (_, final_lv), stacked = jax.lax.scan(
        step, (jnp.bool_(True), tuple(lvs)), None, length=max_iter)
    return tuple(stacked) + tuple(final_lv)


def _cond_fcompute(attrs, pred, *arrays):
    import jax
    from ..subgraph import exec_subgraph
    then_sym = _inner(attrs["then_json"])
    else_sym = _inner(attrs["else_json"])
    in_names = _names(attrs["in_names"])
    vals = dict(zip(in_names, arrays))

    def then_f(vs):
        return tuple(exec_subgraph(then_sym, vs, all_outputs=True))

    def else_f(vs):
        return tuple(exec_subgraph(else_sym, vs, all_outputs=True))

    out = jax.lax.cond(pred.astype(bool).reshape(()), then_f, else_f, vals)
    return out


_registry.register("_foreach", num_outputs="num_outputs")(_foreach_fcompute)
_registry.register("_while_loop",
                   num_outputs="num_outputs")(_while_loop_fcompute)
_registry.register("_cond", num_outputs="num_outputs")(_cond_fcompute)
