"""Symbol: the declarative graph IR.

Re-design of reference nnvm Symbol/Graph (python/mxnet/symbol/symbol.py:55 +
the vendored nnvm C++ graph). A Symbol is a DAG of _SymNode (op + attrs +
input entries) with a list of output entries. JSON serde keeps the MXNet
format (nodes / arg_nodes / heads) so reference model-zoo JSON files load.

Executor story (reference: src/executor/graph_executor.cc): bind() returns an
Executor that traces the whole graph into ONE jitted XLA computation —
memory planning, op fusion, and scheduling (PlanMemory / bulking in the
reference) all delegated to XLA.
"""
from __future__ import annotations

import json

import numpy as np

from ..base import MXNetError, np_dtype
from ..ops import registry as _registry


class _SymNode:
    """One graph node (op instance or variable)."""

    __slots__ = ("op", "name", "attrs", "inputs")

    def __init__(self, op, name, attrs=None, inputs=None):
        self.op = op              # str op name, or None for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = list(inputs or [])  # list[(node, out_index)]

    def is_variable(self):
        return self.op is None


def _auto_name(hint):
    from ..name import NameManager
    return NameManager._current_value().get(None, hint)


def _single_output(s):
    """The (node, idx) of a single-output symbol; composition inputs must
    be scalar-valued in the graph sense."""
    if len(s._outputs) != 1:
        raise MXNetError(
            "cannot compose with a multi-output symbol as one input; "
            "select an output first")
    return s._outputs[0]


class Symbol:
    """Symbol is symbolic graph handle (parity: symbol/symbol.py:55)."""

    def __init__(self, outputs):
        # outputs: list[(node, out_index)]
        self._outputs = list(outputs)

    # -- construction ------------------------------------------------------
    @staticmethod
    def _create(op_name, input_syms, attrs, name=None, named_inputs=None):
        op = _registry.get(op_name)
        attrs = {k: v for k, v in attrs.items() if v is not None}
        from ..attribute import AttrScope
        attrs = AttrScope._current_value().get(attrs)
        from ..name import NameManager
        name = NameManager._current_value().get(name, op_name.lower().strip("_"))

        one_output = _single_output
        entries = [one_output(s) for s in input_syms]
        expected = op.resolve_input_names(attrs)
        named_inputs = dict(named_inputs or {})
        if named_inputs:
            # role-named Symbol inputs (weight=shared_w — the reference
            # weight-tying idiom); only ops declaring input_names take them
            if expected is None:
                raise MXNetError(
                    f"operator {op_name} does not declare named inputs; "
                    f"pass {sorted(named_inputs)} positionally")
            unknown = set(named_inputs) - set(expected)
            if unknown:
                raise MXNetError(
                    f"unknown input name(s) {sorted(unknown)} for operator "
                    f"{op_name}; declared inputs are {list(expected)}")
            clash = set(expected[:len(entries)]) & set(named_inputs)
            if clash:
                raise MXNetError(
                    f"input(s) {sorted(clash)} of {op_name} given both "
                    "positionally and by name")
        # auto-create parameter variables the caller omitted (reference
        # generated-wrapper behavior: sym.FullyConnected(data, num_hidden=k)
        # synthesizes fc_weight/fc_bias vars; BatchNorm's moving stats land
        # in list_auxiliary_states via mutate_aux)
        if expected is not None and len(entries) < len(expected):
            aux_idx = set(op.resolve_mutate_aux(attrs))
            for i in range(len(entries), len(expected)):
                role = expected[i]
                if role in named_inputs:
                    entries.append(one_output(named_inputs.pop(role)))
                    continue
                var_attrs = {"__is_aux__": True} if i in aux_idx else None
                entries.append(
                    (_SymNode(None, f"{name}_{role}", var_attrs), 0))
        node = _SymNode(op_name, name, attrs, entries)
        n_out = op.resolve_num_outputs(attrs)
        # aux-mutating ops (BatchNorm moving stats): user-facing outputs only;
        # the executor routes the trailing outputs back into the aux inputs
        n_out -= len(op.resolve_mutate_aux(attrs))
        # hidden outputs (FNumVisibleOutputs parity, e.g. box_nms's index
        # record) are not part of the composable surface
        if op.num_visible is not None:
            n_out = min(n_out, op.num_visible)
        if n_out == 1:
            return Symbol([(node, 0)])
        return Symbol([(node, i) for i in range(n_out)])

    # -- basic properties --------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        name = self.name
        if name is None:
            name = ", ".join(n.name for n, _ in self._outputs)
            return f"<Symbol group [{name}]>"
        return f"<Symbol {name}>"

    def __iter__(self):
        return (self[i] for i in range(len(self._outputs)))

    def __len__(self):
        return len(self._outputs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            idx = names.index(index)
            return Symbol([self._outputs[idx]])
        if isinstance(index, slice):
            return Group([Symbol([o]) for o in self._outputs[index]])
        return Symbol([self._outputs[index]])

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __deepcopy__(self, memo):
        # a REAL graph clone: _compose mutates nodes in place, so copies
        # meant for independent composition (Symbol.__call__, the C ABI's
        # MXSymbolCopy) must not share nodes with the original.  The node
        # cache rides `memo`, so deepcopying a structure holding several
        # symbols with shared subgraphs preserves that sharing among the
        # clones.
        if id(self) in memo:
            return memo[id(self)]

        def clone(node):
            got = memo.get(id(node))
            if got is None:
                got = _SymNode(node.op, node.name, dict(node.attrs),
                               [(clone(c), oi) for c, oi in node.inputs])
                memo[id(node)] = got
            return got

        out = Symbol([(clone(n), oi) for n, oi in self._outputs])
        memo[id(self)] = out
        return out

    # -- graph walks -------------------------------------------------------
    def _topo(self):
        """Topological order of all nodes reachable from outputs."""
        seen = set()
        order = []

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for (src, _) in node.inputs:
                visit(src)
            order.append(node)

        for (n, _) in self._outputs:
            visit(n)
        return order

    def list_arguments(self):
        """Names of all variable (argument) nodes in topo order."""
        return [n.name for n in self._topo()
                if n.is_variable() and not n.attrs.get("__is_aux__")]

    def list_auxiliary_states(self):
        """Aux states: variables marked auxiliary (BatchNorm moving stats)."""
        return [n.name for n in self._topo()
                if n.is_variable() and n.attrs.get("__is_aux__")]

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_variable()]

    def list_outputs(self):
        outs = []
        for (n, i) in self._outputs:
            if n.is_variable():
                outs.append(n.name)
                continue
            op = _registry.get(n.op)
            n_out = op.num_outputs
            if (isinstance(n_out, int) and n_out > 1) or not isinstance(n_out, int):
                outs.append(f"{n.name}_output{i}")
            else:
                outs.append(f"{n.name}_output")
        return outs

    def get_internals(self):
        """Symbol grouping every internal output (parity: get_internals)."""
        entries = []
        for n in self._topo():
            if n.is_variable():
                entries.append((n, 0))
            else:
                op = _registry.get(n.op)
                n_out = op.resolve_num_outputs(n.attrs)
                for i in range(n_out):
                    entries.append((n, i))
        return Symbol(entries)

    def get_children(self):
        if len(self._outputs) != 1:
            raise MXNetError("get_children on multi-output symbol")
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Symbol(list(node.inputs))

    @property
    def attr_dict(self):
        ret = {}
        for n in self._topo():
            if n.attrs:
                ret[n.name] = {k: str(v) for k, v in n.attrs.items()}
        return ret

    def attr(self, key):
        if len(self._outputs) == 1:
            v = self._outputs[0][0].attrs.get(key)
            return None if v is None else str(v)
        return None

    # -- composition sugar -------------------------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return Symbol._create(op, [a, b], {})
        return Symbol._create(scalar_op, [self], {"scalar": float(other)})

    def __add__(self, o):
        return self._binary(o, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, o):
        if isinstance(o, Symbol):
            return o.__sub__(self)
        return Symbol._create("_rminus_scalar", [self], {"scalar": float(o)})

    def __mul__(self, o):
        return self._binary(o, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, o):
        if isinstance(o, Symbol):
            return o.__truediv__(self)
        return Symbol._create("_rdiv_scalar", [self], {"scalar": float(o)})

    def __pow__(self, o):
        return self._binary(o, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return Symbol._create("negative", [self], {})

    def __eq__(self, o):
        return self._binary(o, "broadcast_equal", "_equal_scalar")

    def __ne__(self, o):
        return self._binary(o, "broadcast_not_equal", "_not_equal_scalar")

    def __gt__(self, o):
        return self._binary(o, "broadcast_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binary(o, "broadcast_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binary(o, "broadcast_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binary(o, "broadcast_lesser_equal", "_lesser_equal_scalar")

    def __hash__(self):
        return id(self)

    def __getattr__(self, name):
        # method-style op application: sym.reshape(...), sym.sum(...)
        if name.startswith("_"):
            raise AttributeError(name)
        if _registry.exists(name):
            def method(*args, **kwargs):
                return Symbol._create(name, [self] + [a for a in args
                                                      if isinstance(a, Symbol)],
                                      {k: v for k, v in kwargs.items()})
            return method
        raise AttributeError(f"Symbol has no attribute {name}")

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, *args, **kwargs):
        """Infer shapes of arguments/outputs/aux given some known shapes
        (parity: symbol.py infer_shape). Returns (arg_shapes, out_shapes,
        aux_shapes)."""
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def infer_type_partial(self, *args, **kwargs):
        """Partial dtype inference (parity: symbol.py infer_type_partial).
        infer_type already reports None for the genuinely unresolvable
        instead of raising, which is exactly the partial contract."""
        return self.infer_type(*args, **kwargs)

    # -- composition -------------------------------------------------------
    def _compose(self, *args, name=None, **kwargs):
        """In-place composition (parity: symbol.py __call__ -> MXSymbolCompose,
        c_api.h:1168): bind this symbol's free-variable inputs to other
        symbols, positionally (list_arguments order) or by variable name."""
        if args and kwargs:
            raise MXNetError(
                "compose accepts positional OR keyword symbols, not both")
        if args:
            free = self.list_arguments()
            if len(args) > len(free):
                raise MXNetError(
                    f"too many positional arguments: {len(args)} given, "
                    f"{len(free)} free variables ({free})")
            kwargs = dict(zip(free, args))
        bad = [k for k, v in kwargs.items() if not isinstance(v, Symbol)]
        if bad:
            raise MXNetError(f"compose values must be Symbols: {bad}")
        repl = {n: _single_output(s) for n, s in kwargs.items()}
        unknown = set(repl) - set(self.list_arguments())
        if unknown:
            raise MXNetError(
                f"compose: {sorted(unknown)} are not free variables of "
                f"this symbol (arguments: {self.list_arguments()})")
        for node in self._topo():
            for i, (child, oi) in enumerate(node.inputs):
                if child.is_variable() and child.name in repl:
                    node.inputs[i] = repl[child.name]
        self._outputs = [
            repl[n.name] if n.is_variable() and n.name in repl else (n, oi)
            for (n, oi) in self._outputs]
        if name is not None and self._outputs:
            # never rename a node grafted in from an ARGUMENT symbol (it
            # stays shared with the caller's graph); only nodes that were
            # already ours take the composed name
            head = self._outputs[0][0]
            if id(head) not in {id(n) for (n, _) in repl.values()}:
                head.name = name

    def __call__(self, *args, **kwargs):
        """Compose into a NEW symbol, leaving this one untouched."""
        import copy as _copy
        out = _copy.deepcopy(self)
        out._compose(*args, **kwargs)
        return out

    def _infer_shape_impl(self, partial, *args, **kwargs):
        import jax
        import jax.numpy as jnp

        known = {}
        if args:
            for name, shape in zip(self.list_arguments(), args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        shapes = {}   # (node,idx) -> shape or None
        dtypes = {}
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        topo = self._topo()

        for n in topo:
            if n.is_variable():
                sh = known.get(n.name)
                if sh is None:
                    sh_attr = n.attrs.get("__shape__")
                    sh = tuple(sh_attr) if sh_attr else None
                shapes[(n, 0)] = sh
                dtypes[(n, 0)] = np_dtype(n.attrs.get("__dtype__", "float32"))
            else:
                op = _registry.get(n.op)
                in_shapes = [shapes.get((src, i)) for (src, i) in n.inputs]
                if any(s is None for s in in_shapes):
                    # backward inference: ops with parameter inputs declare
                    # how weight shapes follow from data shapes (role of
                    # bidirectional FInferShape in the reference,
                    # infer_graph_attr_pass.cc:94)
                    rule = _PARAM_SHAPE_RULES.get(n.op)
                    if rule is not None:
                        filled = rule(dict(n.attrs), in_shapes)
                        for k, s in enumerate(filled):
                            if in_shapes[k] is None and s is not None:
                                in_shapes[k] = tuple(s)
                                src, i = n.inputs[k]
                                shapes[(src, i)] = tuple(s)
                                if (src, i) not in dtypes:
                                    dtypes[(src, i)] = np.dtype(np.float32)
                if any(s is None for s in in_shapes):
                    if partial:
                        continue
                    missing = [src.name for (src, i) in n.inputs
                               if shapes.get((src, i)) is None]
                    raise MXNetError(
                        f"cannot infer shape for node {n.name}: unknown input "
                        f"shapes for {missing}")
                avals = [jax.ShapeDtypeStruct(s, dtypes.get((src, i),
                                                            np.float32))
                         for s, (src, i) in zip(in_shapes, n.inputs)]
                attrs = dict(n.attrs)
                if op.is_random:
                    import jax.random as jrandom
                    avals = [jax.ShapeDtypeStruct((2,), np.uint32)] + avals
                try:
                    out = op.infer(attrs, *avals)
                except Exception as e:
                    if partial:
                        continue
                    raise MXNetError(
                        f"shape inference failed at node {n.name} ({n.op}): {e}"
                    ) from e
                out_t = out if isinstance(out, (tuple, list)) else (out,)
                for i, o in enumerate(out_t):
                    shapes[(n, i)] = tuple(o.shape)
                    dtypes[(n, i)] = np.dtype(o.dtype)

        def var_shape(name):
            for n in topo:
                if n.is_variable() and n.name == name:
                    return shapes.get((n, 0))
            return None

        arg_shapes = [var_shape(a) for a in arg_names]
        aux_shapes = [var_shape(a) for a in aux_names]
        out_shapes = [shapes.get(o) for o in self._outputs]
        if not partial and any(s is None for s in arg_shapes):
            raise MXNetError("incomplete shape information for arguments")
        return arg_shapes, out_shapes, aux_shapes

    # ops whose inputs legitimately differ in dtype from the output, so
    # unknown inputs must NOT be back-filled from the output dtype
    # (index/condition inputs; Cast decides its own output)
    _TYPE_HETERO_OPS = frozenset((
        "Cast", "cast", "amp_cast", "amp_multicast", "Embedding",
        "embedding", "take", "batch_take", "gather_nd", "scatter_nd",
        "one_hot", "pick", "where", "SequenceMask", "SequenceLast",
        "SequenceReverse", "arange_like", "_contrib_boolean_mask",
        "argmax", "argmin", "topk", "argsort",
    ))
    # for hetero ops: which input's dtype the output follows
    _TYPE_DRIVING_INPUT = {"Embedding": 1, "embedding": 1, "where": 1}

    def infer_type(self, *args, **kwargs):
        """Infer dtypes of arguments/outputs/aux from the known ones
        (parity: symbol.py infer_type / the reference InferType pass,
        infer_graph_attr_pass.cc:94 — forward + backward fixpoint).

        Returns (arg_types, out_types, aux_types) as numpy dtypes; an
        entry is None when genuinely unresolvable. With no information
        at all, everything defaults to float32 (the reference's
        variable default)."""
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, t in zip(arg_names, args):
                if t is not None:
                    known[name] = np_dtype(t)
        known.update({k: np_dtype(v) for k, v in kwargs.items()
                      if v is not None})

        topo = self._topo()
        dt = {}  # (node, out_idx) -> np.dtype | None
        for n in topo:
            if n.is_variable():
                d = known.get(n.name)
                if d is None and n.attrs.get("__dtype__"):
                    d = np_dtype(n.attrs["__dtype__"])
                if d is None and not known:
                    # reference default: with zero hints anywhere,
                    # variables resolve to float32 up front so the
                    # whole graph infers complete
                    d = np.dtype(np.float32)
                dt[(n, 0)] = d

        # which output slots of each node are actually consumed
        needed = {}
        for n in topo:
            for (src, i) in n.inputs:
                needed.setdefault(id(src), set()).add(i)
        for (n, i) in self._outputs:
            needed.setdefault(id(n), set()).add(i)

        for _ in range(len(topo)):  # fixpoint: fwd + bwd sweeps
            changed = False
            for n in topo:
                if n.is_variable():
                    continue
                out_keys = [(n, i) for i in needed.get(id(n), {0}) | {0}]
                a = n.attrs
                if n.op in ("Cast", "cast", "amp_cast", "argmax",
                            "argmin", "argsort"):
                    out_d = np_dtype(a.get("dtype", "float32"))
                    for k in out_keys:
                        if dt.get(k) != out_d:
                            dt[k] = out_d
                            changed = True
                    continue
                if n.op in self._TYPE_HETERO_OPS:
                    # output follows one driving input (data/weight/
                    # branch); index & condition inputs are independent,
                    # no backfill. one_hot has no driving input at all —
                    # its dtype attr decides.
                    if n.op == "one_hot":
                        out_d = np_dtype(a.get("dtype", "float32"))
                    else:
                        drive = self._TYPE_DRIVING_INPUT.get(n.op, 0)
                        out_d = (dt.get(n.inputs[drive])
                                 if drive < len(n.inputs) else None)
                    if out_d is not None:
                        for k in out_keys:
                            if dt.get(k) is None:
                                dt[k] = out_d
                                changed = True
                    continue
                # homogeneous op: inputs and outputs form one dtype
                # equivalence class (the reference FInferType idiom) —
                # any known member types every unknown one
                cls = list(n.inputs) + out_keys
                kn = [dt.get(k) for k in cls if dt.get(k) is not None]
                if not kn:
                    continue
                d = np.dtype(np.result_type(*kn))
                for k in cls:
                    if dt.get(k) is None:
                        dt[k] = d
                        changed = True
            if not changed:
                break

        def var_dtype(name):
            for n in topo:
                if n.is_variable() and n.name == name:
                    return dt.get((n, 0))
            return None

        aux_names = self.list_auxiliary_states()
        arg_types = [var_dtype(a) for a in arg_names]
        aux_types = [var_dtype(a) for a in aux_names]
        out_types = [dt.get(o) for o in self._outputs]
        return arg_types, out_types, aux_types

    # -- serde (MXNet JSON format) ------------------------------------------
    def tojson(self):
        """Serialize in the MXNet graph JSON format (parity: sym.tojson;
        reference format produced by nnvm::Graph JSON pass)."""
        topo = self._topo()
        node_index = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        for n in topo:
            entry = {
                "op": "null" if n.is_variable() else n.op,
                "name": n.name,
                "inputs": [[node_index[id(src)], i, 0] for (src, i) in n.inputs],
            }
            attrs = {k: str(v) for k, v in n.attrs.items()
                     if not k.startswith("__")}
            if attrs:
                entry["attrs"] = attrs
            nodes.append(entry)
        arg_nodes = [i for i, n in enumerate(topo) if n.is_variable()]
        heads = [[node_index[id(n)], i, 0] for (n, i) in self._outputs]
        return json.dumps({
            "nodes": nodes,
            "arg_nodes": arg_nodes,
            "node_row_ptr": list(range(len(nodes) + 1)),
            "heads": heads,
            "attrs": {"mxnet_version": ["int", 10500]},
        }, indent=2)

    def save(self, fname):
        # atomic (temp + os.replace): a crash mid-save must not tear an
        # existing symbol file (same contract as nd.save / checkpoint)
        import os
        tmp = f"{fname}.tmp-{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(self.tojson())
            os.replace(tmp, fname)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- evaluation --------------------------------------------------------
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        from .executor import Executor
        from ..subgraph import apply_backend
        return Executor(apply_backend(self), ctx, args, args_grad, grad_req,
                        aux_states, group2ctx=group2ctx)

    def simple_bind(self, ctx, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        """Allocate arguments automatically and bind
        (parity: symbol.py simple_bind → GraphExecutor::Init)."""
        from .. import ndarray as nd
        from .executor import Executor
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        type_dict = type_dict or {}
        args = {}
        args_grad = {}
        for name, shape in zip(arg_names, arg_shapes):
            dtype = type_dict.get(name, np.float32)
            args[name] = nd.zeros(shape, ctx=ctx, dtype=dtype)
            if grad_req != "null":
                args_grad[name] = nd.zeros(shape, ctx=ctx, dtype=dtype)
        aux_states = {name: nd.zeros(shape, ctx=ctx)
                      for name, shape in zip(aux_names, aux_shapes)}
        from ..subgraph import apply_backend
        return Executor(apply_backend(self), ctx, args, args_grad or None,
                        grad_req, aux_states, group2ctx=group2ctx)

    def bind_dict(self, ctx, arg_dict, grad_req="null"):
        """Convenience: bind with a name->NDArray dict covering all inputs."""
        from .executor import Executor
        return Executor(self, ctx, arg_dict, None, grad_req, None)

    def eval(self, ctx=None, **kwargs):
        from ..context import current_context
        ctx = ctx or current_context()
        ex = self.bind_dict(ctx, kwargs)
        return ex.forward()

    def tostype(self, stype):
        if stype == "default":
            return self
        raise NotImplementedError("sparse symbol storage conversion")


def _fc_shapes(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes
    nh = int(attrs["num_hidden"])
    flatten = bool(attrs.get("flatten", True))
    in_units = int(np.prod(data[1:])) if flatten else data[-1]
    out = [data, (nh, in_units)]
    if len(in_shapes) > 2:
        out.append((nh,))
    return out


def _conv_shapes(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes
    nf = int(attrs["num_filter"])
    groups = int(attrs.get("num_group", 1))
    kernel = tuple(attrs["kernel"])
    layout = attrs.get("layout") or ("NCW", "NCHW", "NCDHW")[len(kernel) - 1]
    c = data[layout.find("C")]
    out = [data, (nf, c // groups) + kernel]
    if len(in_shapes) > 2:
        out.append((nf,))
    return out


def _deconv_shapes(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes
    nf = int(attrs["num_filter"])
    groups = int(attrs.get("num_group", 1))
    kernel = tuple(attrs["kernel"])
    layout = attrs.get("layout") or ("NCW", "NCHW", "NCDHW")[len(kernel) - 1]
    c = data[layout.find("C")]
    out = [data, (c, nf // groups) + kernel]
    if len(in_shapes) > 2:
        out.append((nf,))
    return out


def _norm_shapes(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes
    axis = int(attrs.get("axis", 1))
    c = data[axis % len(data)]
    return [data] + [(c,)] * (len(in_shapes) - 1)


def _layernorm_shapes(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes
    axis = int(attrs.get("axis", -1))
    c = data[axis % len(data)]
    return [data] + [(c,)] * (len(in_shapes) - 1)


def _embedding_shapes(attrs, in_shapes):
    return [in_shapes[0],
            (int(attrs["input_dim"]), int(attrs["output_dim"]))]


def _rnn_shapes(attrs, in_shapes):
    data = in_shapes[0]
    if data is None:
        return in_shapes
    from ..ops._op_nn import rnn_param_size
    mode = attrs["mode"]
    hidden = int(attrs["state_size"])
    layers = int(attrs["num_layers"])
    bidir = bool(attrs.get("bidirectional", False))
    dirs = 2 if bidir else 1
    T, N, I = data
    psize = rnn_param_size(mode, layers, I, hidden, bidir)
    out = [data, (psize,), (layers * dirs, N, hidden)]
    if len(in_shapes) > 3:
        out.append((layers * dirs, N, hidden))
    return out


def _prelu_shapes(attrs, in_shapes):
    data = in_shapes[0]
    if data is None or len(in_shapes) < 2:
        return in_shapes
    return [data, (data[1] if len(data) > 1 else 1,)]


def _softmax_output_shapes(attrs, in_shapes):
    # label defaults to data minus the class axis (reference
    # softmax_output.cc SoftmaxOutputShape) — lets inference-only binds
    # proceed without label_shapes
    data = in_shapes[0]
    if data is None or len(in_shapes) < 2:
        return in_shapes
    return [data, tuple(data[:-1]) if len(data) > 1 else (1,)]


def _regression_output_shapes(attrs, in_shapes):
    # label shape == data shape (reference regression_output-inl.h)
    data = in_shapes[0]
    if data is None or len(in_shapes) < 2:
        return in_shapes
    return [data, data]


_PARAM_SHAPE_RULES = {
    "FullyConnected": _fc_shapes,
    "Convolution": _conv_shapes,
    "Deconvolution": _deconv_shapes,
    "BatchNorm": _norm_shapes,
    "InstanceNorm": _norm_shapes,
    "GroupNorm": lambda attrs, s: _norm_shapes({**attrs, "axis": 1}, s),
    "LayerNorm": _layernorm_shapes,
    "Embedding": _embedding_shapes,
    "RNN": _rnn_shapes,
    "LeakyReLU": _prelu_shapes,
    "SoftmaxOutput": _softmax_output_shapes,
    "Softmax": _softmax_output_shapes,
    "LinearRegressionOutput": _regression_output_shapes,
    "MAERegressionOutput": _regression_output_shapes,
    "LogisticRegressionOutput": _regression_output_shapes,
}


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs):
    """Create a variable symbol (parity: symbol.py var/Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable `name`")
    from ..attribute import AttrScope
    attrs = AttrScope._current_value().get(dict(attr or {}))
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = np_dtype(dtype).name
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        if not isinstance(init, str):
            init = init.dumps()
        attrs["__init__"] = init
    attrs.update(kwargs)
    node = _SymNode(None, name, attrs)
    return Symbol([(node, 0)])


Variable = var


def Group(symbols):
    """Create a symbol grouping outputs of `symbols` (parity: sym.Group)."""
    entries = []
    for s in symbols:
        entries.extend(s._outputs)
    return Symbol(entries)


# Optimizer/placement hints that old JSONs store as PLAIN attrs; modern
# graphs (and this framework) expect them in `__key__` form on the
# variable they apply to (reference src/nnvm/legacy_json_util.cc
# kHiddenKeys + UpgradeJSON_FixParsing).
_HIDDEN_KEYS = ("ctx_group", "lr_mult", "wd_mult", "force_mirroring",
                "mirror_stage")


def _upgrade_legacy_attrs(entry, node, input_names):
    """One node's legacy-JSON upgrade (reference legacy_json_util.cc):

    * pre-0.9 graphs keep op params under ``param`` — fold them in;
    * bare hidden keys (``lr_mult`` on a node) become ``__lr_mult__``;
    * suffixed hidden keys (``weight_lr_mult`` on an OP node) move onto
      the matching input VARIABLE as ``__lr_mult__``.
    """
    attrs = dict(entry.get("attrs", entry.get("attr", {}) or {}))
    attrs.update(entry.get("param", {}) or {})
    out = {}
    deferred = []  # (input_name, hidden_key, value)
    for k, v in attrs.items():
        hidden = next((h for h in _HIDDEN_KEYS
                       if k == h or k.endswith("_" + h)), None)
        if hidden is None:
            out[k] = v
        elif k == hidden:
            out[f"__{hidden}__"] = v
        else:
            deferred.append((k[:-(len(hidden) + 1)], hidden, v))
    node.attrs.update({k: _parse_attr_value(v) for k, v in out.items()})
    for arg_name, hidden, v in deferred:
        for (src, _oi), role in zip(node.inputs, input_names or []):
            if src.is_variable() and role == arg_name:
                src.attrs[f"__{hidden}__"] = _parse_attr_value(v)
                break
        else:  # no matching input: keep it where it was (reference does)
            node.attrs[f"{arg_name}_{hidden}"] = _parse_attr_value(v)


def load_json(json_str):
    """Load symbol from MXNet graph JSON (parity: sym.load_json; also reads
    reference-produced files — format from nnvm JSON pass, including
    pre-1.0 graphs via the legacy upgrade path)."""
    data = json.loads(json_str)
    raw_nodes = data["nodes"]
    nodes = []
    for entry in raw_nodes:
        op = entry["op"]
        node = _SymNode(None if op == "null" else op, entry["name"], {})
        node.inputs = [(nodes[src], out_i)
                       for src, out_i, *_ in entry.get("inputs", [])]
        input_names = None
        if node.op is not None:
            # resolve roles from the SAME folded attr view the upgrade
            # uses — pre-0.9 graphs keep op params under 'param', and
            # role resolution (e.g. no_bias) must see them
            folded = dict(entry.get("attrs", entry.get("attr", {}) or {}))
            folded.update(entry.get("param", {}) or {})
            try:
                input_names = _registry.get(node.op).resolve_input_names(
                    {k: _parse_attr_value(v) for k, v in folded.items()})
            except Exception:
                input_names = None
        _upgrade_legacy_attrs(entry, node, input_names)
        nodes.append(node)
    heads = [(nodes[i], out_i) for i, out_i, *_ in data["heads"]]
    return Symbol(heads)


def _parse_attr_value(v):
    """Parse MXNet string attr values: '(3, 3)' → tuple, 'True' → bool, …"""
    if not isinstance(v, str):
        return v
    s = v.strip()
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        pass
    if s.startswith("(") and s.endswith(")") or \
            s.startswith("[") and s.endswith("]"):
        inner = s[1:-1].strip()
        if not inner:
            return ()
        try:
            # "(4,)" splits to ["4", ""] — drop the trailing empty segment
            return tuple(_parse_attr_value(x) for x in inner.split(",")
                         if x.strip())
        except Exception:
            return s
    return s


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def zeros(shape, dtype=None, **kwargs):
    return Symbol._create("_zeros", [], {"shape": tuple(shape),
                                         "dtype": np_dtype(dtype or "float32").name})


def ones(shape, dtype=None, **kwargs):
    return Symbol._create("_ones", [], {"shape": tuple(shape),
                                        "dtype": np_dtype(dtype or "float32").name})


def arange(start, stop=None, step=1.0, repeat=1, name=None, dtype=None):
    return Symbol._create("_arange", [], {
        "start": start, "stop": stop, "step": step, "repeat": repeat,
        "dtype": np_dtype(dtype or "float32").name}, name=name)
