"""mx.sym — the symbolic namespace (parity: python/mxnet/symbol/)."""
from .symbol import (Symbol, Group, Variable, var, load, load_json, zeros,
                     ones, arange)
from . import contrib  # noqa: F401
from . import image  # noqa: F401
from ..ops import registry as _registry


def _make_sym_func(op):
    def fn(*args, name=None, attr=None, **kwargs):
        inputs = [a for a in args if isinstance(a, Symbol)]
        scalars = [a for a in args
                   if not isinstance(a, Symbol)
                   and isinstance(a, (int, float, bool, str, tuple, list))]
        for attr_name, val in zip(op.scalar_args, scalars):
            kwargs.setdefault(attr_name, val)
        # Symbol-valued kwargs are INPUTS named by role (reference generated
        # wrappers accept e.g. weight=shared_w for weight tying); they must
        # not fall into attrs or the auto-create path would silently shadow
        # them with fresh variables.
        sym_kw = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        for k in sym_kw:
            del kwargs[k]
        return Symbol._create(op.name, inputs, kwargs, name=name,
                              named_inputs=sym_kw)

    fn.__name__ = op.name
    fn.__doc__ = f"Symbolic wrapper for operator `{op.name}`."
    return fn


_SYM_FUNC_CACHE = {}


def __getattr__(name):
    if _registry.exists(name):
        if name not in _SYM_FUNC_CACHE:
            _SYM_FUNC_CACHE[name] = _make_sym_func(_registry.get(name))
        return _SYM_FUNC_CACHE[name]
    raise AttributeError(f"module 'mxnet_tpu.symbol' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_registry.list_ops()))
