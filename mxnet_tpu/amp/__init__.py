"""Automatic mixed precision (parity: python/mxnet/contrib/amp/amp.py:250).

``amp.init()`` turns on dispatch-level precision routing: allow-listed ops
(the MXU matmul/conv family) cast fp32 float inputs down to the target
dtype, deny-listed ops cast low-precision inputs up to fp32, and widest-
type ops promote mixed inputs — the role of the reference's
low_precision_pass.cc graph rewrite, applied at op dispatch so it covers
the imperative path AND everything traced through it (hybridize,
functionalize, TrainStep).  The casts live INSIDE each op's differentiated
function, so backward transposes them: low-precision compute, fp32
gradient accumulation, fp32 master weights.

Default target is bfloat16 — the TPU-native low precision (fp32 exponent
range: no loss scaling needed).  fp16 + dynamic LossScaler is supported
for parity.
"""
from __future__ import annotations

import contextlib

from ..base import MXNetError
from . import lists
from .loss_scaler import LossScaler

_STATE = {
    "active": False,
    "target_dtype": None,
    "low_ops": frozenset(),
    "fp32_ops": frozenset(),
    "widest_ops": frozenset(),
}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP (parity: amp.py:250 — patches the op namespaces; here it
    arms the dispatch hook in ndarray.invoke via op attrs)."""
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    low = set(lists.LOW_PRECISION_OPS)
    if target_precision_ops is not None:
        low |= set(target_precision_ops)
    f32 = set(lists.FP32_OPS)
    if fp32_ops is not None:
        f32 |= set(fp32_ops)
    if conditional_fp32_ops is not None:
        f32 |= {name for (name, _attr, _vals) in conditional_fp32_ops}
    _STATE.update(active=True, target_dtype=target_dtype,
                  low_ops=frozenset(low - f32), fp32_ops=frozenset(f32),
                  widest_ops=frozenset(lists.WIDEST_TYPE_CASTS))


def deinit():
    """Disable AMP (test helper; the reference has no public off-switch)."""
    _STATE.update(active=False, target_dtype=None, low_ops=frozenset(),
                  fp32_ops=frozenset(), widest_ops=frozenset())


def is_active():
    return _STATE["active"]


def amp_mode_for(op_name):
    """The '_amp' attr value for an op under the current AMP state, or
    None.  Consulted by ndarray.invoke at dispatch."""
    if not _STATE["active"]:
        return None
    if op_name in _STATE["low_ops"]:
        return "low:" + _STATE["target_dtype"]
    if op_name in _STATE["fp32_ops"]:
        return "f32:" + _STATE["target_dtype"]
    if op_name in _STATE["widest_ops"]:
        return "widest:" + _STATE["target_dtype"]
    return None


# -- loss scaling ------------------------------------------------------------
def init_trainer(optimizer_or_trainer):
    """Attach a dynamic loss scaler to a Trainer (parity: amp.py:287)."""
    from ..gluon.trainer import Trainer
    if isinstance(optimizer_or_trainer, Trainer):
        optimizer_or_trainer._amp_loss_scaler = LossScaler()
        optimizer_or_trainer._amp_original_scale = \
            optimizer_or_trainer._scale
    else:
        raise MXNetError("init_trainer expects a gluon.Trainer")


@contextlib.contextmanager
def scale_loss(loss, optimizer_or_trainer):
    """``with amp.scale_loss(loss, trainer) as scaled: scaled.backward()``
    (parity: amp.py:240).  Scales the loss up; trainer.step unscales the
    gradients and skips the update on overflow."""
    scaler = getattr(optimizer_or_trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    optimizer_or_trainer._scale = (
        optimizer_or_trainer._amp_original_scale / scaler.loss_scale)
    if isinstance(loss, (list, tuple)):
        yield [l * scaler.loss_scale for l in loss]
    else:
        yield loss * scaler.loss_scale


def unscale(optimizer_or_trainer):
    """Explicitly unscale gradients (parity: amp.py:330) — for use with
    trainer.allreduce_grads()/update() split steps.  Restores the
    trainer's rescale factor so update() does not divide by the loss
    scale a second time; the scaler's dynamic state is untouched."""
    scaler = getattr(optimizer_or_trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for param in optimizer_or_trainer._params:
        if param.grad_req != "null" and param._grad is not None:
            for g in param.list_grad():
                g._set_data(g._data * inv)
    optimizer_or_trainer._scale = \
        optimizer_or_trainer._amp_original_scale


# -- model conversion --------------------------------------------------------
def convert_model(sym, arg_params, aux_params, target_dtype="bfloat16",
                  target_dtype_ops=None, fp32_ops=None,
                  conditional_fp32_ops=None, excluded_sym_names=None,
                  cast_optional_params=False):
    """Cast a symbolic model's parameters for low-precision inference
    (parity: amp.py:508).  Norm/aux statistics stay fp32;
    excluded_sym_names keeps named params in fp32.  Op-level precision
    lists are applied at dispatch by amp.init(), not by this parameter
    cast — passing them here warns."""
    import numpy as np
    import warnings
    if target_dtype_ops or fp32_ops or conditional_fp32_ops:
        warnings.warn(
            "convert_model casts parameters only; op-level precision "
            "lists are applied at dispatch — pass them to amp.init()")
    excluded = set(excluded_sym_names or [])
    new_args = {}
    for k, v in arg_params.items():
        if k not in excluded and v.dtype == np.float32 and v.ndim > 1:
            new_args[k] = v.astype(target_dtype)
        else:
            new_args[k] = v
    return sym, new_args, dict(aux_params)


def convert_hybrid_block(block, target_dtype="bfloat16"):
    """Cast a gluon block's matrix/conv parameters to the target dtype
    for inference (vector params — norms, biases — stay fp32)."""
    for p in block.collect_params().values():
        if p._data is not None:
            d = p.data()
            if len(d.shape) > 1 and str(d.dtype) == "float32":
                p.cast(target_dtype)  # set_data would coerce back to p.dtype
    return block


def all_finite(*arrays):
    """True iff every array is free of inf/nan (reference all_finite op)."""
    import jax.numpy as jnp
    ok = True
    for a in arrays:
        data = a._data if hasattr(a, "_data") else a
        ok = ok and bool(jnp.isfinite(data).all())
    return ok
