"""AMP op lists (role parity: python/mxnet/contrib/amp/lists/symbol.py).

Reference semantics: FP16_FUNCS run in low precision, FP32_FUNCS are forced
to full precision, WIDEST_TYPE_CASTS promote mixed inputs.  TPU defaults
target bfloat16 — same exponent range as fp32, so the deny list is shorter
than the reference's fp16 one (no loss-scaling-critical softmax/exp cases),
but reductions, norms and losses still accumulate in fp32.
"""

# the MXU ops — where low precision pays
LOW_PRECISION_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "RNN",
    "dot", "batch_dot",
]

# numerically sensitive: force fp32 inputs
FP32_OPS = [
    "softmax", "log_softmax", "SoftmaxActivation", "SoftmaxOutput",
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "LRN",
    "L2Normalization", "norm",
    "exp", "log", "log2", "log10", "log1p", "expm1",
    "mean", "sum", "nansum", "prod", "nanprod",
    "CTCLoss", "MakeLoss", "LinearRegressionOutput",
    "LogisticRegressionOutput", "MAERegressionOutput",
    "smooth_l1", "SVMOutput",
]

# elementwise combiners where mixed inputs should promote
WIDEST_TYPE_CASTS = [
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "add_n", "where", "broadcast_maximum", "broadcast_minimum",
    "broadcast_power", "maximum", "minimum",
]
