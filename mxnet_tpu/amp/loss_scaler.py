"""Dynamic loss scaler (parity: python/mxnet/contrib/amp/loss_scaler.py).

With bfloat16 (TPU default) scaling is rarely needed — bf16 shares fp32's
exponent range — but the capability is kept for fp16 workflows and API
parity: multiply the loss up, check gradients for inf/nan, halve the scale
on overflow, double it after a streak of clean steps.
"""
from __future__ import annotations

import numpy as np


class LossScaler:
    def __init__(self, init_scale=2. ** 16, scale_factor=2.,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, grads):
        """True if any gradient array contains inf/nan.  All per-array
        checks reduce into ONE scalar before the single host sync
        (reference: fused multi_all_finite op)."""
        import jax.numpy as jnp
        checks = [jnp.isfinite(g._data if hasattr(g, "_data") else g).all()
                  for g in grads if g is not None]
        if not checks:
            return False
        return not bool(jnp.stack(checks).all())

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1)
            self._unskipped = 0
        else:
            self._unskipped += 1
        if self._unskipped == self._scale_window:
            self.loss_scale = min(self.loss_scale * self._scale_factor,
                                  2. ** 24)
            self._unskipped = 0
