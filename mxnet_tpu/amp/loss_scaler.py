"""Dynamic loss scaler (parity: python/mxnet/contrib/amp/loss_scaler.py).

With bfloat16 (TPU default) scaling is rarely needed — bf16 shares fp32's
exponent range — but the capability is kept for fp16 workflows and API
parity: multiply the loss up, check gradients for inf/nan, halve the scale
on overflow, double it after a streak of clean steps.

The overflow check shares the numerics observatory's fused sentinel
(ISSUE 14 satellite): :meth:`has_overflow` delegates to
``telemetry.numerics.host_all_finite`` — ONE jitted multi-all-finite
reduction + one host sync, the same idiom the in-window non-finite flag
uses — instead of building its own per-array ``isfinite().all()`` list
every step.  When a numerics-armed train step already computed the
per-step flags inside its donated window, attach the scaler
(``telemetry.numerics.attach_loss_scaler``) and the boundary check feeds
its backoff/growth directly — no separate device sync at all.  The
backoff/growth sequence is unchanged either way (parity-tested in
tests/test_amp.py).
"""
from __future__ import annotations


class LossScaler:
    def __init__(self, init_scale=2. ** 16, scale_factor=2.,
                 scale_window=2000, tolerance=0.05):
        self.loss_scale = init_scale
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, grads):
        """True if any gradient array contains inf/nan — one fused
        device reduction + one host sync via the shared numerics
        sentinel (reference: fused multi_all_finite op)."""
        from ..telemetry import numerics as _numerics
        return not _numerics.host_all_finite(grads)

    def update_scale(self, overflow):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1)
            self._unskipped = 0
        else:
            self._unskipped += 1
        if self._unskipped == self._scale_window:
            self.loss_scale = min(self.loss_scale * self._scale_factor,
                                  2. ** 24)
            self._unskipped = 0

    def update_from_window(self, overflow_flags):
        """Feed one window's per-step overflow verdicts (the in-window
        non-finite flags a numerics-armed train step already computed)
        — the same backoff/growth sequence as ``scale_window`` many
        ``update_scale`` calls, with zero extra device syncs."""
        for flag in overflow_flags:
            self.update_scale(bool(flag))
