"""Core shared definitions: dtypes, errors, registry helpers.

TPU-native re-design of the roles played by dmlc-core in the reference
(``include/mxnet/base.h``, dmlc ``LOG/CHECK`` and ``dmlc::Parameter``): here
Python + JAX provide typing/logging, and op parameters are plain keyword
attributes validated per-op.
"""
from __future__ import annotations

import logging
import numpy as np

__version__ = "0.1.0"

logger = logging.getLogger("mxnet_tpu")


class MXNetError(RuntimeError):
    """Error raised by the runtime (parity: MXNetError in python/mxnet/base.py)."""


class PeerLostError(MXNetError):
    """A multi-host peer stopped heartbeating (preemption, eviction,
    crash) while this process was — or would have been — waiting on it.

    Raised by the kvstore server's dead-peer propagation (an in-flight
    sync pull or barrier that can only complete with the dead rank's
    participation fails typed instead of timing out generically) and by
    the multi-host runtime's window rendezvous/peer probes.  Carries the
    lost ``ranks`` so the elastic recovery path knows the survivor set.
    Not retryable: the peer is gone; recovery is a boundary checkpoint +
    elastic restore onto the survivor mesh (docs/parallel.md).
    """

    retryable = False

    def __init__(self, ranks, detail=""):
        self.ranks = tuple(int(r) for r in (
            ranks if isinstance(ranks, (list, tuple, set)) else [ranks]))
        super().__init__(
            f"peer(s) {sorted(self.ranks)} lost (no heartbeat within the "
            "peer timeout)" + (f": {detail}" if detail else ""))


class PreemptionError(MXNetError):
    """This host received a preemption notice (SIGTERM) and must leave
    the mesh at the next window boundary.  The elastic session turns it
    into a boundary checkpoint + clean handoff (docs/parallel.md)."""

    retryable = False


class NonFiniteError(MXNetError):
    """The numerics observatory detected non-finite values (NaN/Inf).

    Raised at a train-window boundary under ``MXNET_NUMERICS=halt``
    (the poisoned update was already applied — restore from
    ``dump_path``'s ``last_good_checkpoint_step`` and replay), and by
    the serving output-health guard when a model produces non-finite
    logits (that request fails typed; it is never served).  Not
    retryable: resubmitting the same computation reproduces the same
    poison (docs/observability.md numerics runbook).
    """

    retryable = False

    def __init__(self, where, step=None, stat=None, value=None,
                 dump_path=None, detail=""):
        self.where = where
        self.step = step
        self.stat = stat
        self.value = value
        self.dump_path = dump_path
        msg = f"non-finite values detected in {where}"
        if stat is not None:
            msg += f" ({stat}={value!r}"
            msg += f" at step {step})" if step is not None else ")"
        if detail:
            msg += f": {detail}"
        if dump_path:
            msg += f" — forensics: {dump_path}"
        super().__init__(msg)


# TPU integer-width contract -------------------------------------------------
# The backend narrows int64 to int32 (TPU integer units are 32-bit; the
# reference builds with int64 tensor indexing, tests/nightly/
# test_large_array.py).  That narrowing is a documented deviation, but it
# must be LOUD: any size, dim, or index beyond int32 raises MXNetError at
# the API boundary instead of letting JAX truncate with a warning.
INT32_MAX = 2 ** 31 - 1


def check_int32_range(value, what):
    """Raise MXNetError when ``value`` cannot be represented as int32."""
    if value > INT32_MAX:
        raise MXNetError(
            f"{what} {value} exceeds the int32 limit {INT32_MAX}: the "
            "TPU backend uses 32-bit integer indexing (large-tensor int64 "
            "support is a documented deviation, docs/env_var.md); "
            "refusing to truncate silently")
    return value


def check_shape_int32(shape, allow_wildcards=False, what="array"):
    """Validate every dim AND the total element count against int32.

    The single guard behind the creation APIs (zeros/ones/full/array),
    NDArray.reshape, and the host-parameterized generators (arange /
    linspace).  ``allow_wildcards`` skips non-positive dims (reshape's
    0/-1/-2.. placeholders).  Returns the validated element count.
    """
    size = 1
    for d in shape:
        d = int(d)
        if d <= 0 and allow_wildcards:
            continue
        size *= check_int32_range(d, "dimension")
    check_int32_range(size, f"{what} size")
    return size


# dtype handling -------------------------------------------------------------
# The reference maps int codes <-> numpy dtypes (mshadow type codes). We keep
# the same code assignment for checkpoint compatibility (NDArray binary format
# stores these codes; see reference src/ndarray/ndarray.cc Save/Load).
_DTYPE_NP_TO_MX = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.int64): 6,
    # TPU-era addition (not in the v1.5 reference wire format):
    np.dtype("bfloat16") if hasattr(np, "bfloat16") else "bfloat16": 7,
    np.dtype(np.bool_): 8,
}
_DTYPE_MX_TO_NP = {}
for _k, _v in list(_DTYPE_NP_TO_MX.items()):
    _DTYPE_MX_TO_NP[_v] = _k

try:  # ml_dtypes ships with jax; gives us a real bfloat16 numpy dtype
    import ml_dtypes as _ml_dtypes

    bfloat16 = np.dtype(_ml_dtypes.bfloat16)
    _DTYPE_NP_TO_MX[bfloat16] = 7
    _DTYPE_MX_TO_NP[7] = bfloat16
except ImportError:  # pragma: no cover
    bfloat16 = None


def np_dtype(dtype):
    """Normalise a user-provided dtype spec to a numpy dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str) and dtype == "bfloat16" and bfloat16 is not None:
        return bfloat16
    return np.dtype(dtype)


def dtype_code(dtype):
    d = np_dtype(dtype)
    if d not in _DTYPE_NP_TO_MX:
        raise MXNetError(f"unsupported dtype {d}")
    return _DTYPE_NP_TO_MX[d]


def dtype_from_code(code):
    if code not in _DTYPE_MX_TO_NP:
        raise MXNetError(f"unknown dtype code {code}")
    return _DTYPE_MX_TO_NP[code]


# string constants mirroring GradReq (include/mxnet/op_attr_types.h OpReqType)
GRAD_REQ_MAP = {"null": 0, "write": 1, "add": 3}


def check_call(ret):  # parity shim: no C ABI here, everything is in-process
    return ret


class _NameManager:
    """Automatic unique naming (parity: python/mxnet/name.py NameManager)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"


name_manager = _NameManager()
