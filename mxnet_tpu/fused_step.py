"""Fused train step: forward + backward + optimizer update as ONE donated
XLA computation per step.

The reference engine dispatches the train step as hundreds of engine pushes
(forward graph, backward graph, one optimizer op + one grad-zeroing write
PER PARAMETER — ~320 host-side dispatches/step for ResNet-50).  PyGraph
(arXiv 2503.19779) and μ-cuDNN (arXiv 1804.04806) both show that capturing
the whole step into one executable is the largest step-time win on
accelerator-bound loops; the TPU equivalent is one ``jax.jit`` over
forward + VJP + the whole-pytree optimizer update, with ``donate_argnums``
on weights, optimizer state and aux stats so XLA reuses the buffers
in place.

Contracts kept:

* **Bit parity** with the per-param loop for every optimizer exposing
  ``fused_update`` (SGD/momentum/multi-precision, Adam): the trace mirrors
  the executor's ``fwd_vjp`` formulation (same cotangents, same grad
  dtype casts) and the per-op update math, and consumes ONE
  ``random.next_key()`` per step like ``Executor.forward``.
* **Views stay consistent**: after a step the module's ``arg_dict`` /
  ``aux_dict`` NDArrays hold the new buffers, ``grad_dict`` reads as
  zeros (write-mode semantics, served from cached zero buffers — no
  dispatch), optimizer state lives in the SAME ``Updater.states``
  NDArrays, and ``exec.outputs`` carries the forward outputs — metrics,
  monitors-off checkpointing and ``get_optimizer_states`` work unchanged.
* **No recompiles across lr schedules**: lr/wd (and Adam's bias
  correction) are evaluated host-side once per step by
  ``Optimizer.fused_hyperparams`` and passed as weak-typed scalar
  arguments.
* **Donation safety**: buffers that were not produced by this step's own
  jit output (externally set params, freshly restored optimizer state)
  are defensively copied before being donated, so arrays the user still
  holds are never invalidated.

Opt-out: ``MXNET_FUSED_STEP=0`` (config.py).  Ineligible setups (kvstore,
monitors, custom optimizers without ``fused_update``, grad_req "add",
group2ctx) silently keep the per-param loop; ``python -m
mxnet_tpu.fused_step`` is the CI smoke asserting <= 3 dispatches/step and
loop parity.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

from . import profiler as _prof
from . import random as _random
from . import telemetry as _telemetry
from .base import MXNetError
from .ndarray import NDArray

log = logging.getLogger(__name__)


def _as_buf(x):
    return x._data if isinstance(x, NDArray) else x


class FusedTrainStep:
    """One-dispatch train step bound to a Module's executor + optimizer."""

    def __init__(self, module):
        exec_ = module._exec
        self._module = module
        self._exec_ref = exec_
        self._opt_ref = module._optimizer
        self._arg_names = list(exec_._arg_names)
        self._aux_names = list(exec_._aux_names)
        # trainable = optimizer-updated: grad_req "write" (eligibility
        # already excluded "add"); fixed/"null" params are frozen on both
        # paths
        self._train = [(i, n) for i, n in enumerate(module._param_names)
                       if exec_.grad_req.get(n, "null") == "write"]
        if not self._train:
            raise MXNetError("fused step: no trainable parameters")
        self._train_names = [n for _, n in self._train]
        self._opt_indices = [i for i, _ in self._train]
        train_set = set(self._train_names)
        self._train_slots = [self._arg_names.index(n)
                             for n in self._train_names]
        self._other_names = [n for n in self._arg_names
                             if n not in train_set]
        self._other_slots = [self._arg_names.index(n)
                             for n in self._other_names]
        self._feed_names = set(module._data_names) | \
            set(module._label_names)
        self._device = module._context.jax_device
        # ownership ledger: buffers produced by OUR last jit call may be
        # donated freely; anything else could still be referenced outside
        # (user-held arg_params, restored optimizer state) and is copied
        # once before its first donation
        self._owned = {}
        self._static_sig = None
        self._jit = None
        self._trace_count = 0  # bumped at trace time; tests assert == 1
        self.steps = 0

    # -- trace -------------------------------------------------------------
    def _build_jit(self):
        module = self._module
        fn = module._exec._build_fn(True)
        opt = module._optimizer
        n_args = len(self._arg_names)
        train_slots = tuple(self._train_slots)
        other_slots = tuple(self._other_slots)
        outer = self

        def step(key, train_vals, other_vals, aux_vals, states, lrs, wds):
            outer._trace_count += 1  # host side effect: runs at trace only

            def fwd(*tv):
                full = [None] * n_args
                for slot, v in zip(train_slots, tv):
                    full[slot] = v
                for slot, v in zip(other_slots, other_vals):
                    full[slot] = v
                return fn(key, tuple(full), aux_vals)

            # mirror Executor.forward(is_train=True)+backward(): vjp over
            # the trainable args, all-ones cotangents on the outputs,
            # zeros on the mutated aux, grads cast to the weight dtype
            (outs, new_aux), vjp_fn = jax.vjp(fwd, *train_vals)
            cts = tuple(jnp.ones_like(o) for o in outs)
            zero_aux = tuple(jnp.zeros_like(a) for a in new_aux)
            grads = vjp_fn((cts, zero_aux))
            grads = [g.astype(w.dtype) for g, w in zip(grads, train_vals)]
            new_params, new_states = opt.fused_update(
                list(train_vals), grads, list(states),
                list(lrs), list(wds))
            return outs, new_aux, tuple(new_params), new_states

        # donate weights (1), aux stats (3) and optimizer state (4):
        # XLA aliases them onto the matching outputs — in-place reuse,
        # and grad buffers never materialize between dispatches at all
        self._jit = jax.jit(step, donate_argnums=(1, 3, 4))

    # -- per-step host path ------------------------------------------------
    def _owned_or_copy(self, token, buf):
        if self._owned.get(token) is buf:
            return buf
        # not produced by our own last step: copy so donation cannot
        # invalidate an alias the caller still holds (set_params shares
        # buffers with the user's arg_params dict)
        return buf.copy()

    def step(self, data_batch):
        """Run one fused step.  Returns False (caller falls back to the
        per-param loop) when the batch doesn't match the bound shapes —
        partial final batches take the reshape path like before."""
        module = self._module
        exec_ = module._exec
        feed = {}
        for desc, arr in zip(module._data_shapes, data_batch.data):
            feed[desc.name] = arr
        if module._label_shapes and data_batch.label:
            for desc, arr in zip(module._label_shapes, data_batch.label):
                feed[desc.name] = arr
        for name, arr in feed.items():
            bound = exec_.arg_dict.get(name)
            if bound is None or tuple(arr.shape) != tuple(bound.shape):
                return False

        opt = module._optimizer
        sig = opt.fused_static_signature()
        if self._jit is None or sig != self._static_sig:
            self._build_jit()
            self._static_sig = sig

        # stage the feed: device placement + the same dtype cast the
        # arg_dict[:]= path applies (no-ops when already staged/typed)
        dev = self._device
        feed_bufs = {}
        for name, arr in feed.items():
            buf = _as_buf(arr)
            if dev not in buf.devices():
                buf = jax.device_put(buf, dev)
            bound = exec_.arg_dict[name]
            if buf.dtype != bound._data.dtype:
                buf = buf.astype(bound._data.dtype)
            feed_bufs[name] = buf

        # optimizer state: create lazily through the SAME Updater the
        # loop path uses, so checkpoint get/set_optimizer_states and a
        # later fallback to the loop see one state store
        updater = module._updater
        for i, name in self._train:
            updater._ensure_state(i, exec_.arg_dict[name])
        states_nd = [updater.states[i] for i in self._opt_indices]

        train_vals = tuple(
            self._owned_or_copy(("p", n), exec_.arg_dict[n]._data)
            for n in self._train_names)
        aux_vals = tuple(
            self._owned_or_copy(("a", n), exec_.aux_dict[n]._data)
            for n in self._aux_names)
        leaf_counter = [0]

        def stage_state(leaf):
            tok = ("s", leaf_counter[0])
            leaf_counter[0] += 1
            return self._owned_or_copy(tok, _as_buf(leaf))

        states = jax.tree_util.tree_map(stage_state, states_nd)
        other_vals = tuple(
            feed_bufs[n] if n in feed_bufs else exec_.arg_dict[n]._data
            for n in self._other_names)

        # host-side hyperparameter evaluation ONCE per step (satellite:
        # lr schedules must not bake into the trace): bump the update
        # counts first, exactly like each per-param update() call does
        for i in self._opt_indices:
            opt._update_count(i)
        lrs, wds = opt.fused_hyperparams(self._opt_indices)

        key = _random.next_key()
        with _telemetry.span("fit/step/fused_dispatch"):
            outs, new_aux, new_params, new_states = self._jit(
                key, train_vals, other_vals, aux_vals, states,
                tuple(lrs), tuple(wds))
        _prof.record_dispatch("fused_step")

        # write-back: swap the NEW buffers into the existing NDArray
        # views so arg_dict/aux_dict/updater.states stay the canonical
        # handles (zero extra dispatches — these are reference swaps)
        owned = {}
        for name, buf in zip(self._train_names, new_params):
            exec_.arg_dict[name]._set_data(buf)
            owned[("p", name)] = buf
        for name, buf in zip(self._aux_names, new_aux):
            exec_.aux_dict[name]._set_data(buf)
            owned[("a", name)] = buf
        leaf_counter[0] = 0

        def writeback_state(old, new):
            tok = ("s", leaf_counter[0])
            leaf_counter[0] += 1
            owned[tok] = new
            old._set_data(new)

        jax.tree_util.tree_map(writeback_state, states_nd, new_states)
        for name, buf in feed_bufs.items():
            exec_.arg_dict[name]._set_data(buf)
        self._owned = owned

        module._zero_grads()
        exec_.outputs = [NDArray(o, module._context) for o in outs]
        exec_._vjp_holder = None
        exec_._last_is_train = True
        self.steps += 1
        _prof.record_counter("train:fused_step_total", self.steps)
        return True

    def stale(self, module):
        return (module._exec is not self._exec_ref
                or module._optimizer is not self._opt_ref)


def _smoke():
    """CI gate: the fused path must issue <= 3 framework dispatches per
    step and match the per-param loop bitwise (run via
    ``python -m mxnet_tpu.fused_step``; see ci/run.sh)."""
    import os
    import sys

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio

    def build():
        d = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(d, num_hidden=64, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    rng = np.random.RandomState(0)
    x = rng.randn(32, 50).astype(np.float32)
    y = rng.randint(0, 10, 32).astype(np.float32)
    batch = mxio.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    init = {"fc1_weight": mx.nd.array(rng.randn(64, 50) * 0.1),
            "fc1_bias": mx.nd.zeros((64,)),
            "fc2_weight": mx.nd.array(rng.randn(10, 64) * 0.1),
            "fc2_bias": mx.nd.zeros((10,))}

    def run(fused, steps=5):
        os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
        mx.random.seed(0)
        mod = mx.mod.Module(build(), context=mx.cpu())
        mod.bind(data_shapes=[("data", x.shape)],
                 label_shapes=[("softmax_label", y.shape)])
        mod.init_params(arg_params={k: v.copy() for k, v in init.items()})
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        mod.forward_backward(batch)
        mod.update()  # warm: compiles outside the counted window
        mx.profiler.reset_dispatch_counts()
        for _ in range(steps):
            mod.forward_backward(batch)
            mod.update()
        counts = mx.profiler.dispatch_counts()
        params, _ = mod.get_params()
        return counts, {k: v.asnumpy() for k, v in params.items()}

    counts_f, params_f = run(True)
    counts_l, params_l = run(False)
    per_step = counts_f.get("total", 0) / 5
    print(f"fused: {per_step:.1f} dispatches/step {counts_f}; "
          f"loop: {counts_l.get('total', 0) / 5:.1f} {counts_l}")
    if per_step > 3:
        print("FAIL: fused path exceeds 3 dispatches/step", file=sys.stderr)
        sys.exit(1)
    if counts_f.get("fused_step", 0) != 5:
        print("FAIL: fused step did not engage", file=sys.stderr)
        sys.exit(1)
    for k in params_f:
        if not np.array_equal(params_f[k], params_l[k]):
            print(f"FAIL: fused/loop parity broke on {k}", file=sys.stderr)
            sys.exit(1)
    print("fused step smoke OK: <=3 dispatches/step, bitwise loop parity")


if __name__ == "__main__":
    _smoke()
