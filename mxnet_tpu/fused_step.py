"""Fused train step: forward + backward + optimizer update as ONE donated
XLA computation per step.

The reference engine dispatches the train step as hundreds of engine pushes
(forward graph, backward graph, one optimizer op + one grad-zeroing write
PER PARAMETER — ~320 host-side dispatches/step for ResNet-50).  PyGraph
(arXiv 2503.19779) and μ-cuDNN (arXiv 1804.04806) both show that capturing
the whole step into one executable is the largest step-time win on
accelerator-bound loops; the TPU equivalent is one ``jax.jit`` over
forward + VJP + the whole-pytree optimizer update, with ``donate_argnums``
on weights, optimizer state and aux stats so XLA reuses the buffers
in place.

Contracts kept:

* **Bit parity** with the per-param loop for every optimizer exposing
  ``fused_update`` (SGD/momentum/multi-precision, Adam): the trace mirrors
  the executor's ``fwd_vjp`` formulation (same cotangents, same grad
  dtype casts) and the per-op update math, and consumes ONE
  ``random.next_key()`` per step like ``Executor.forward``.
* **Views stay consistent**: after a step the module's ``arg_dict`` /
  ``aux_dict`` NDArrays hold the new buffers, ``grad_dict`` reads as
  zeros (write-mode semantics, served from cached zero buffers — no
  dispatch), optimizer state lives in the SAME ``Updater.states``
  NDArrays, and ``exec.outputs`` carries the forward outputs — metrics,
  monitors-off checkpointing and ``get_optimizer_states`` work unchanged.
* **No recompiles across lr schedules**: lr/wd (and Adam's bias
  correction) are evaluated host-side once per step by
  ``Optimizer.fused_hyperparams`` and passed as weak-typed scalar
  arguments.
* **Donation safety**: buffers that were not produced by this step's own
  jit output (externally set params, freshly restored optimizer state)
  are defensively copied before being donated, so arrays the user still
  holds are never invalidated.

Opt-out: ``MXNET_FUSED_STEP=0`` (config.py).  Ineligible setups (kvstore,
monitors, custom optimizers without ``fused_update``, grad_req "add",
group2ctx) silently keep the per-param loop; ``python -m
mxnet_tpu.fused_step`` is the CI smoke asserting <= 3 dispatches/step and
loop parity.
"""
from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
import numpy as np

from . import profiler as _prof
from . import random as _random
from . import telemetry as _telemetry
from .base import MXNetError
from .ndarray import NDArray
from .telemetry import numerics as _numerics

log = logging.getLogger(__name__)


def _as_buf(x):
    return x._data if isinstance(x, NDArray) else x


class FusedTrainStep:
    """One-dispatch train step bound to a Module's executor + optimizer."""

    def __init__(self, module):
        exec_ = module._exec
        self._module = module
        self._exec_ref = exec_
        self._opt_ref = module._optimizer
        self._arg_names = list(exec_._arg_names)
        self._aux_names = list(exec_._aux_names)
        # trainable = optimizer-updated: grad_req "write" (eligibility
        # already excluded "add"); fixed/"null" params are frozen on both
        # paths
        self._train = [(i, n) for i, n in enumerate(module._param_names)
                       if exec_.grad_req.get(n, "null") == "write"]
        if not self._train:
            raise MXNetError("fused step: no trainable parameters")
        self._train_names = [n for _, n in self._train]
        self._opt_indices = [i for i, _ in self._train]
        train_set = set(self._train_names)
        self._train_slots = [self._arg_names.index(n)
                             for n in self._train_names]
        self._other_names = [n for n in self._arg_names
                             if n not in train_set]
        self._other_slots = [self._arg_names.index(n)
                             for n in self._other_names]
        self._feed_names = set(module._data_names) | \
            set(module._label_names)
        self._device = module._context.jax_device
        # ownership ledger: buffers produced by OUR last jit call may be
        # donated freely; anything else could still be referenced outside
        # (user-held arg_params, restored optimizer state) and is copied
        # once before its first donation
        self._owned = {}
        self._static_sig = None
        self._jit = None
        self._trace_count = 0  # bumped at trace time; tests assert == 1
        self._just_built = False  # next dispatch carries the compile
        # numerics observatory (ISSUE 14): mode + stat bucket plan are
        # baked into the trace signature — arming retraces, never drifts
        self._num_mode = "off"
        self._num_poison = False
        self._num_groups = []
        self._num_labels = []
        self.steps = 0

    def _numerics_plan(self):
        """Freeze the observatory mode + stat buckets for the next
        trace (dtype-contiguous parameter groups, same rule as the
        collective planner, so a poisoned bucket names a model region).
        The poison-injection multiply is baked in only while the chaos
        ``train/poison_grad`` site is armed."""
        exec_ = self._module._exec
        self._num_mode = _numerics.trace_mode()
        self._num_poison = False
        if self._num_mode == "off":
            self._num_groups, self._num_labels = [], []
            return
        self._num_poison = _numerics.poison_armed()
        shapes = [tuple(exec_.arg_dict[n].shape)
                  for n in self._train_names]
        dtypes = [str(exec_.arg_dict[n]._data.dtype)
                  for n in self._train_names]
        self._num_groups, self._num_labels = _numerics.stat_groups(
            shapes, dtypes, names=self._train_names)

    def _numerics_sig(self):
        """The observatory's contribution to the trace signature."""
        return (_numerics.trace_mode(),
                _numerics.trace_mode() != "off" and
                _numerics.poison_armed())

    # -- trace -------------------------------------------------------------
    def _build_jit(self):
        # compilation lifecycle (ISSUE 7): artifacts persist across
        # processes, and every rebuild is a ledger event — a retrace
        # storm shows up in mxnet_compile_traces_total, not in step time
        from . import compile as _compile
        _compile.ensure_persistent_cache()
        _compile.record_trace(
            "fused_step",
            "build" if self._jit is None else "signature-change")
        self._just_built = True
        module = self._module
        fn = module._exec._build_fn(True)
        opt = module._optimizer
        n_args = len(self._arg_names)
        train_slots = tuple(self._train_slots)
        other_slots = tuple(self._other_slots)
        self._numerics_plan()
        num_mode = self._num_mode
        num_groups = self._num_groups
        num_poison = self._num_poison
        outer = self

        def step(key, train_vals, other_vals, aux_vals, states, lrs, wds,
                 poison):
            outer._trace_count += 1  # host side effect: runs at trace only

            def fwd(*tv):
                full = [None] * n_args
                for slot, v in zip(train_slots, tv):
                    full[slot] = v
                for slot, v in zip(other_slots, other_vals):
                    full[slot] = v
                return fn(key, tuple(full), aux_vals)

            # mirror Executor.forward(is_train=True)+backward(): vjp over
            # the trainable args, all-ones cotangents on the outputs,
            # zeros on the mutated aux, grads cast to the weight dtype
            (outs, new_aux), vjp_fn = jax.vjp(fwd, *train_vals)
            cts = tuple(jnp.ones_like(o) for o in outs)
            zero_aux = tuple(jnp.zeros_like(a) for a in new_aux)
            grads = vjp_fn((cts, zero_aux))
            grads = [g.astype(w.dtype) for g, w in zip(grads, train_vals)]
            if num_poison:
                # chaos train/poison_grad rides this scalar (1.0 = IEEE
                # identity, bitwise no-op; NaN/Inf poisons the window);
                # baked in only while the site is armed, so production
                # armed windows pay zero extra gradient traffic
                grads = [g * poison.astype(g.dtype) for g in grads]
            new_params, new_states = opt.fused_update(
                list(train_vals), grads, list(states),
                list(lrs), list(wds))
            if num_mode != "off":
                # numerics observatory (ISSUE 14): health stats ride the
                # same donated dispatch; skip mode gates the poisoned
                # update on device (the loss-scaler idiom, no extra sync)
                new_params, (new_aux, new_states), stats = \
                    _numerics.trace_step(
                        num_mode, grads, list(outs), train_vals,
                        new_params, [(new_aux, aux_vals),
                                     (new_states, states)], num_groups)
                stats = _numerics.window_param_stats(
                    stats, new_params, train_vals)
                return outs, new_aux, tuple(new_params), new_states, stats
            return outs, new_aux, tuple(new_params), new_states, ()

        # donate weights (1), aux stats (3) and optimizer state (4):
        # XLA aliases them onto the matching outputs — in-place reuse,
        # and grad buffers never materialize between dispatches at all
        self._jit = jax.jit(step, donate_argnums=(1, 3, 4))

    # -- per-step host path ------------------------------------------------
    def _owned_or_copy(self, token, buf, sharding=None):
        if self._owned.get(token) is buf:
            return buf
        # not produced by our own last step: copy so donation cannot
        # invalidate an alias the caller still holds (set_params shares
        # buffers with the user's arg_params dict).  A mesh-fused
        # subclass passes its parameter ``sharding`` so externally-set
        # buffers (single-device restores, user arg_params) land
        # replicated/sharded on the mesh before their first donation.
        buf = buf.copy()
        if sharding is not None and buf.sharding != sharding:
            buf = jax.device_put(buf, sharding)
        return buf

    def _stage_carry(self, sharding=None):
        """Stage the donated carry: ``(train_vals, aux_vals, states,
        states_nd)`` with every buffer either produced by our own last
        dispatch (donate freely) or ledger-copied (and re-placed onto
        ``sharding`` when given).  Optimizer state is created lazily
        through the SAME ``Updater`` the loop path uses, so checkpoint
        get/set_optimizer_states and a later fallback to the loop see
        one state store."""
        module = self._module
        exec_ = module._exec
        updater = module._updater
        for i, name in self._train:
            updater._ensure_state(i, exec_.arg_dict[name])
        states_nd = [updater.states[i] for i in self._opt_indices]
        train_vals = tuple(
            self._owned_or_copy(("p", n), exec_.arg_dict[n]._data, sharding)
            for n in self._train_names)
        aux_vals = tuple(
            self._owned_or_copy(("a", n), exec_.aux_dict[n]._data, sharding)
            for n in self._aux_names)
        leaf_counter = [0]

        def stage_state(leaf):
            tok = ("s", leaf_counter[0])
            leaf_counter[0] += 1
            return self._owned_or_copy(tok, _as_buf(leaf), sharding)

        states = jax.tree_util.tree_map(stage_state, states_nd)
        return train_vals, aux_vals, states, states_nd

    def _writeback_carry(self, tv, av, st, states_nd):
        """Swap the NEW buffers into the existing NDArray views so
        arg_dict/aux_dict/updater.states stay the canonical handles
        (zero extra dispatches — these are reference swaps), and record
        them in the ownership ledger for the next donation."""
        exec_ = self._module._exec
        owned = {}
        for name, buf in zip(self._train_names, tv):
            exec_.arg_dict[name]._set_data(buf)
            owned[("p", name)] = buf
        for name, buf in zip(self._aux_names, av):
            exec_.aux_dict[name]._set_data(buf)
            owned[("a", name)] = buf
        leaf_counter = [0]

        def writeback_state(old, new):
            tok = ("s", leaf_counter[0])
            leaf_counter[0] += 1
            owned[tok] = new
            old._set_data(new)

        jax.tree_util.tree_map(writeback_state, states_nd, st)
        self._owned = owned

    def step(self, data_batch):
        """Run one fused step.  Returns False (caller falls back to the
        per-param loop) when the batch doesn't match the bound shapes —
        partial final batches take the reshape path like before."""
        module = self._module
        exec_ = module._exec
        feed = {}
        for desc, arr in zip(module._data_shapes, data_batch.data):
            feed[desc.name] = arr
        if module._label_shapes and data_batch.label:
            for desc, arr in zip(module._label_shapes, data_batch.label):
                feed[desc.name] = arr
        for name, arr in feed.items():
            bound = exec_.arg_dict.get(name)
            if bound is None or tuple(arr.shape) != tuple(bound.shape):
                return False

        opt = module._optimizer
        sig = (opt.fused_static_signature(), self._numerics_sig())
        if self._jit is None or sig != self._static_sig:
            self._build_jit()
            self._static_sig = sig

        # stage the feed: device placement + the same dtype cast the
        # arg_dict[:]= path applies (no-ops when already staged/typed)
        dev = self._device
        feed_bufs = {}
        for name, arr in feed.items():
            buf = _as_buf(arr)
            if dev not in buf.devices():
                buf = jax.device_put(buf, dev)
            bound = exec_.arg_dict[name]
            if buf.dtype != bound._data.dtype:
                buf = buf.astype(bound._data.dtype)
            feed_bufs[name] = buf

        train_vals, aux_vals, states, states_nd = self._stage_carry()
        if self._just_built:
            # resource observatory (ISSUE 13): a (re)build re-states the
            # donated carry's device footprint — host shape math only,
            # never on the steady-state per-step path
            _telemetry.resources.account_train_step(
                "fused_step", params=train_vals, opt_state=states,
                aux=aux_vals)
        other_vals = tuple(
            feed_bufs[n] if n in feed_bufs else exec_.arg_dict[n]._data
            for n in self._other_names)

        # host-side hyperparameter evaluation ONCE per step (satellite:
        # lr schedules must not bake into the trace): bump the update
        # counts first, exactly like each per-param update() call does
        for i in self._opt_indices:
            opt._update_count(i)
        lrs, wds = opt.fused_hyperparams(self._opt_indices)

        key = _random.next_key()
        poison = _numerics.poison_value() if self._num_poison \
            else np.float32(1.0)
        with _telemetry.span("fit/step/fused_dispatch"):
            if self._just_built:
                # first dispatch after a (re)trace: charge its backend
                # compile to the fused step in the TraceLedger
                from . import compile as _compile
                with _compile.LEDGER.attribute("fused_step"):
                    outs, new_aux, new_params, new_states, stats = \
                        self._jit(key, train_vals, other_vals, aux_vals,
                                  states, tuple(lrs), tuple(wds), poison)
                self._just_built = False
            else:
                outs, new_aux, new_params, new_states, stats = self._jit(
                    key, train_vals, other_vals, aux_vals, states,
                    tuple(lrs), tuple(wds), poison)
        _prof.record_dispatch("fused_step")

        self._writeback_carry(new_params, new_aux, new_states, states_nd)
        for name, buf in feed_bufs.items():
            exec_.arg_dict[name]._set_data(buf)

        module._zero_grads()
        exec_.outputs = [NDArray(o, module._context) for o in outs]
        exec_._vjp_holder = None
        exec_._last_is_train = True
        self.steps += 1
        _prof.record_counter("train:fused_step_total", self.steps)
        if self._num_mode != "off":
            # boundary check: one tiny host read; halt mode raises typed
            # NonFiniteError here, AFTER the views are consistent
            _numerics.observe_window(
                stats, kind="fused_step", first_step=self.steps,
                window=self.steps, group_labels=self._num_labels)
        return True

    def stale(self, module):
        return (module._exec is not self._exec_ref
                or module._optimizer is not self._opt_ref)


class ScanTrainStep(FusedTrainStep):
    """K fused train steps as ONE donated XLA dispatch (``jax.lax.scan``).

    The fused step body (forward + VJP + optimizer update) becomes the
    scan body; weights / optimizer state / aux stats are the carry, the
    staged super-batch (one stacked array per input, leading dims
    ``(K, M)``) and the host-evaluated per-step lr/wd vectors are the
    scanned inputs, and the per-step forward outputs come back stacked so
    metric updates at the window boundary see exactly what K sequential
    steps would have produced.  With ``accum`` M > 1 each scan step
    consumes M micro-batches sequentially (aux threads through, like M
    forwards would) and applies ONE update over their summed gradients —
    in-scan gradient accumulation for effective batches beyond HBM.

    Host control (metric flush, callbacks, checkpoint triggers, watchdog
    beats) happens only at window boundaries — the fit loop owns that
    contract (module._fit_epoch_scan)."""

    def __init__(self, module, scan_steps, accum=1):
        super().__init__(module)
        self.scan_steps = max(1, int(scan_steps))
        self.accum = max(1, int(accum))
        self._scan_jit = None
        self._scan_sig = None
        self._feed_order = None
        self._rest_names = []
        self._scan_trace_count = 0  # tests assert == 1 across an epoch
        self.windows = 0

    @property
    def window_batches(self):
        return self.scan_steps * self.accum

    # -- trace -------------------------------------------------------------
    def _build_scan_jit(self):
        from . import compile as _compile
        _compile.ensure_persistent_cache()
        _compile.record_trace(
            "scan_step",
            "build" if self._scan_jit is None else "signature-change")
        self._just_built = True
        module = self._module
        fn = module._exec._build_fn(True)
        opt = module._optimizer
        n_args = len(self._arg_names)
        n_train = len(self._train_names)
        train_slots = tuple(self._train_slots)
        feed_slots = tuple(self._arg_names.index(n)
                           for n in self._feed_order)
        feed_set = set(self._feed_order)
        self._rest_names = [n for n in self._other_names
                            if n not in feed_set]
        rest_slots = tuple(self._arg_names.index(n)
                           for n in self._rest_names)
        accum = self.accum
        self._numerics_plan()
        num_mode = self._num_mode
        num_groups = self._num_groups
        num_poison = self._num_poison
        outer = self

        def window(keys, feeds, lrs, wds, train_vals, rest_vals,
                   aux_vals, states, poison):
            outer._scan_trace_count += 1  # host side: runs at trace only

            def micro(key, feed_vals, train_vals, aux_vals):
                # one forward+VJP, identical math to the single fused step
                def fwd(*tv):
                    full = [None] * n_args
                    for slot, v in zip(train_slots, tv):
                        full[slot] = v
                    for slot, v in zip(feed_slots, feed_vals):
                        full[slot] = v
                    for slot, v in zip(rest_slots, rest_vals):
                        full[slot] = v
                    return fn(key, tuple(full), aux_vals)

                (outs, new_aux), vjp_fn = jax.vjp(fwd, *train_vals)
                cts = tuple(jnp.ones_like(o) for o in outs)
                zero_aux = tuple(jnp.zeros_like(a) for a in new_aux)
                grads = vjp_fn((cts, zero_aux))
                grads = [g.astype(w.dtype)
                         for g, w in zip(grads, train_vals)]
                return outs, new_aux, grads

            def body(carry, xs):
                tv, av, st = carry
                av0 = av
                key_s, feed_s, lr_s, wd_s = xs
                grads_sum = None
                outs_micro = []
                for m in range(accum):
                    outs, av, grads = micro(
                        key_s[m], tuple(f[m] for f in feed_s), tv, av)
                    outs_micro.append(outs)
                    grads_sum = grads if grads_sum is None else \
                        [a + b for a, b in zip(grads_sum, grads)]
                if num_poison:
                    grads_sum = [g * poison.astype(g.dtype)
                                 for g in grads_sum]
                if num_mode != "off":
                    # fusion fence: grads now have two consumers (the
                    # optimizer update AND the stat reductions); without
                    # it XLA CPU duplicates batch-sized backward chains
                    # into each consumer's fusion — measured at >10% of
                    # step wall.  The barrier materializes grads once.
                    grads_sum = list(jax.lax.optimization_barrier(
                        tuple(grads_sum)))
                new_params, new_states = opt.fused_update(
                    list(tv), grads_sum, list(st),
                    [lr_s[i] for i in range(n_train)],
                    [wd_s[i] for i in range(n_train)])
                ys = tuple(jnp.stack([o[i] for o in outs_micro])
                           for i in range(len(outs_micro[0])))
                if num_mode != "off":
                    # in-scan health stats: one extra scanned output, no
                    # extra dispatch; skip mode gates THIS step's update
                    new_params, (av, new_states), stats = \
                        _numerics.trace_step(
                            num_mode, grads_sum, [ys[0]], tv, new_params,
                            [(av, av0), (new_states, st)], num_groups)
                    ys = ys + (stats,)
                return (tuple(new_params), av, new_states), ys

            carry, ys = jax.lax.scan(
                body, (train_vals, aux_vals, states),
                (keys, feeds, lrs, wds))
            tv, av, st = carry
            if num_mode != "off":
                stats = _numerics.window_param_stats(
                    ys[-1], tv, train_vals)
                return tv, av, st, ys[:-1], stats
            return tv, av, st, ys, ()

        # donate the carry inputs (weights / aux / optimizer state): the
        # scan's final carry aliases them in place, exactly like the
        # single-step donation — one buffer set for the whole window
        self._scan_jit = jax.jit(window, donate_argnums=(4, 6, 7))

    # -- per-window host path ----------------------------------------------
    def run_window(self, sbatch):
        """Dispatch one K-step (x M micro-batch) window.  ``sbatch`` is an
        ``io.SuperBatch`` whose data/label arrays are stacked buffers
        with leading dim K*M — device arrays, or host numpy stacks when
        the streaming window feed pre-staged them off-thread
        (``stage_super_batch(host=True)``); jit placement makes the two
        bitwise-equivalent.  Returns the list of per-position output
        buffers flattened to leading dim K*M (for boundary metric
        updates), or False when the window is short or the stacked
        shapes don't match the bound executor (caller falls back to
        per-batch steps)."""
        module = self._module
        exec_ = module._exec
        K, M = self.scan_steps, self.accum
        W = K * M
        if sbatch.count != W:
            return False
        feed = {}
        for desc, arr in zip(module._data_shapes, sbatch.data):
            feed[desc.name] = arr
        if module._label_shapes and sbatch.label:
            for desc, arr in zip(module._label_shapes, sbatch.label):
                feed[desc.name] = arr
        for name, arr in feed.items():
            bound = exec_.arg_dict.get(name)
            if bound is None or \
                    tuple(arr.shape) != (W,) + tuple(bound.shape):
                return False

        opt = module._optimizer
        sig = (opt.fused_static_signature(), K, M,
               self._numerics_sig(),
               tuple(sorted((n, tuple(a.shape), str(a.dtype))
                            for n, a in feed.items())))
        if self._scan_jit is None or sig != self._scan_sig:
            self._feed_order = sorted(feed)
            self._build_scan_jit()
            self._scan_sig = sig

        # stage the stacked feeds: (K, M, *batch_shape), bound dtype
        feed_bufs = []
        for name in self._feed_order:
            buf = feed[name]
            bound = exec_.arg_dict[name]
            if buf.dtype != bound._data.dtype:
                buf = buf.astype(bound._data.dtype)
            feed_bufs.append(buf.reshape((K, M) + tuple(bound.shape)))

        train_vals, aux_vals, states, states_nd = self._stage_carry()
        if self._just_built:
            _telemetry.resources.account_train_step(
                "scan_step", params=train_vals, opt_state=states,
                aux=aux_vals)
        rest_vals = tuple(exec_.arg_dict[n]._data
                          for n in self._rest_names)

        # host-side hyperparameters for the WHOLE window: K rows of
        # lr/wd, update counts bumped per step exactly like K sequential
        # fused steps — schedules advance inside the scan, no retrace
        lrs, wds = opt.fused_window_hyperparams(self._opt_indices, K)
        lrs = np.asarray(lrs, np.float32)
        wds = np.asarray(wds, np.float32)
        # one key per micro forward, same counter stream as W sequential
        # steps (bitwise-identical randomness)
        keys = np.stack([np.asarray(_random.next_key())
                         for _ in range(W)])
        keys = keys.reshape((K, M) + keys.shape[1:])

        poison = _numerics.poison_value() if self._num_poison \
            else np.float32(1.0)
        with _telemetry.span("fit/step/scan_dispatch"):
            if self._just_built:
                from . import compile as _compile
                with _compile.LEDGER.attribute("scan_step"):
                    tv, av, st, ys, stats = self._scan_jit(
                        keys, tuple(feed_bufs), lrs, wds,
                        train_vals, rest_vals, aux_vals, states, poison)
                self._just_built = False
            else:
                tv, av, st, ys, stats = self._scan_jit(
                    keys, tuple(feed_bufs), lrs, wds,
                    train_vals, rest_vals, aux_vals, states, poison)
        _prof.record_dispatch("scan_window")

        self._writeback_carry(tv, av, st, states_nd)

        module._zero_grads()
        # (K, M, *out) -> (K*M, *out): position j is micro-batch j's
        # forward outputs, computed with that step's pre-update weights —
        # the boundary metric sees what W sequential steps produced
        outs_flat = [y.reshape((W,) + tuple(y.shape[2:])) for y in ys]
        exec_.outputs = [NDArray(y[W - 1], module._context)
                         for y in outs_flat]
        exec_._vjp_holder = None
        exec_._last_is_train = True
        self.steps += K
        self.windows += 1
        _prof.record_counter("train:fused_step_total", self.steps)
        if self._num_mode != "off":
            # window-boundary check: the host's only read of the stats
            # (one tiny transfer); halt raises typed NonFiniteError here
            _numerics.observe_window(
                stats, kind="scan_window",
                first_step=self.steps - K + 1, window=self.windows,
                group_labels=self._num_labels)
        return outs_flat


def _smoke():
    """CI gate: the fused path must issue <= 3 framework dispatches per
    step and match the per-param loop bitwise (run via
    ``python -m mxnet_tpu.fused_step``; see ci/run.sh)."""
    import os
    import sys

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio

    def build():
        d = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(d, num_hidden=64, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    rng = np.random.RandomState(0)
    x = rng.randn(32, 50).astype(np.float32)
    y = rng.randint(0, 10, 32).astype(np.float32)
    batch = mxio.DataBatch(data=[mx.nd.array(x)], label=[mx.nd.array(y)])
    init = {"fc1_weight": mx.nd.array(rng.randn(64, 50) * 0.1),
            "fc1_bias": mx.nd.zeros((64,)),
            "fc2_weight": mx.nd.array(rng.randn(10, 64) * 0.1),
            "fc2_bias": mx.nd.zeros((10,))}

    def run(fused, steps=5):
        os.environ["MXNET_FUSED_STEP"] = "1" if fused else "0"
        mx.random.seed(0)
        mod = mx.mod.Module(build(), context=mx.cpu())
        mod.bind(data_shapes=[("data", x.shape)],
                 label_shapes=[("softmax_label", y.shape)])
        mod.init_params(arg_params={k: v.copy() for k, v in init.items()})
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        mod.forward_backward(batch)
        mod.update()  # warm: compiles outside the counted window
        mx.profiler.reset_dispatch_counts()
        for _ in range(steps):
            mod.forward_backward(batch)
            mod.update()
        counts = mx.profiler.dispatch_counts()
        params, _ = mod.get_params()
        return counts, {k: v.asnumpy() for k, v in params.items()}

    counts_f, params_f = run(True)
    counts_l, params_l = run(False)
    per_step = counts_f.get("total", 0) / 5
    print(f"fused: {per_step:.1f} dispatches/step {counts_f}; "
          f"loop: {counts_l.get('total', 0) / 5:.1f} {counts_l}")
    if per_step > 3:
        print("FAIL: fused path exceeds 3 dispatches/step", file=sys.stderr)
        sys.exit(1)
    if counts_f.get("fused_step", 0) != 5:
        print("FAIL: fused step did not engage", file=sys.stderr)
        sys.exit(1)
    for k in params_f:
        if not np.array_equal(params_f[k], params_l[k]):
            print(f"FAIL: fused/loop parity broke on {k}", file=sys.stderr)
            sys.exit(1)
    print("fused step smoke OK: <=3 dispatches/step, bitwise loop parity")


def _scan_smoke():
    """CI gate for the scanned window: at K=8 a fit epoch must issue
    <= (1+eps)/K dispatches per train step and stay bitwise identical to
    the sequential fused loop (run via ``python -m mxnet_tpu.fused_step``
    after the single-step smoke; see ci/run.sh)."""
    import os
    import sys

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import io as mxio

    K, NB, BS = 8, 16, 32  # two full windows per epoch

    def build():
        d = mx.sym.Variable("data")
        h = mx.sym.FullyConnected(d, num_hidden=64, name="fc1")
        h = mx.sym.Activation(h, act_type="relu")
        h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
        return mx.sym.SoftmaxOutput(h, name="softmax")

    rng = np.random.RandomState(0)
    x = rng.randn(NB * BS, 50).astype(np.float32)
    y = rng.randint(0, 10, NB * BS).astype(np.float32)
    init = {"fc1_weight": mx.nd.array(rng.randn(64, 50) * 0.1),
            "fc1_bias": mx.nd.zeros((64,)),
            "fc2_weight": mx.nd.array(rng.randn(10, 64) * 0.1),
            "fc2_bias": mx.nd.zeros((10,))}

    def run(scan_k):
        os.environ["MXNET_FUSED_STEP"] = "1"
        os.environ["MXNET_SCAN_STEPS"] = str(scan_k)
        mx.random.seed(0)
        it = mxio.NDArrayIter(mx.nd.array(x), mx.nd.array(y),
                              batch_size=BS, label_name="softmax_label")
        mod = mx.mod.Module(build(), context=mx.cpu())
        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
                arg_params={k: v.copy() for k, v in init.items()})
        mx.profiler.reset_dispatch_counts()
        it.reset()
        mod.fit(it, num_epoch=1, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
        counts = mx.profiler.dispatch_counts()
        params, _ = mod.get_params()
        return counts, {k: v.asnumpy() for k, v in params.items()}

    counts_s, params_s = run(K)
    counts_q, params_q = run(1)
    os.environ["MXNET_SCAN_STEPS"] = "1"
    per_step = counts_s.get("total", 0) / NB
    budget = (1 + 0.25) / K
    print(f"scan K={K}: {per_step:.3f} dispatches/step {counts_s}; "
          f"sequential: {counts_q.get('total', 0) / NB:.2f} {counts_q}; "
          f"budget {budget:.3f}")
    if counts_s.get("scan_window", 0) != NB // K:
        print("FAIL: scanned window did not engage", file=sys.stderr)
        sys.exit(1)
    if per_step > budget:
        print(f"FAIL: scan path exceeds {budget:.3f} dispatches/step",
              file=sys.stderr)
        sys.exit(1)
    for k in params_s:
        if not np.array_equal(params_s[k], params_q[k]):
            print(f"FAIL: scan/sequential parity broke on {k}",
                  file=sys.stderr)
            sys.exit(1)
    print(f"scan smoke OK: <= {budget:.3f} dispatches/step at K={K}, "
          "bitwise parity with the sequential fused loop")


if __name__ == "__main__":
    _smoke()
    _scan_smoke()
