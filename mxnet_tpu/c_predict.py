"""Python side of the C predict API (driven by src/c_predict_api.cc).

Parity: the reference's standalone predict ABI (c_predict_api.cc) binds a
symbol + params for inference only; here the Predictor wraps a bound
Executor with grad_req='null'. Params arrive as the raw bytes of a
.params file (nd.save format), inputs/outputs as raw float32 buffers.
"""
from __future__ import annotations

import os
import tempfile

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .context import cpu
from .symbol import load_json


def _load_params_bytes(raw):
    """Deserialize nd.save bytes → dict (tolerates arg:/aux: prefixes)."""
    # nd.load reads from a path; spool the bytes through a temp file
    with tempfile.NamedTemporaryFile(suffix=".params", delete=False) as f:
        f.write(raw)
        path = f.name
    try:
        loaded = nd.load(path)
    finally:
        os.unlink(path)
    if not isinstance(loaded, dict):
        raise MXNetError(
            "predictor params must be a NAMED dict save "
            "(nd.save(path, {'arg:name': ...}) / HybridBlock.export); "
            "got an unnamed list save")
    out = {}
    for k, v in loaded.items():
        out[k.split(":", 1)[-1]] = v
    return out


class Predictor:
    def __init__(self, symbol_json, param_bytes, input_shapes):
        self._sym = load_json(symbol_json)
        params = _load_params_bytes(param_bytes)
        arg_names = self._sym.list_arguments()
        aux_names = set(self._sym.list_auxiliary_states())
        self._input_shapes = {k: tuple(int(d) for d in v)
                              for k, v in input_shapes.items()}
        args = {}
        for name in arg_names:
            if name in self._input_shapes:
                args[name] = nd.zeros(self._input_shapes[name])
            elif name in params:
                args[name] = params[name]
            else:
                raise MXNetError(
                    f"predictor: argument {name!r} has neither a bound "
                    "input shape nor a loaded parameter")
        aux = {name: params[name] for name in aux_names if name in params}
        self._exec = self._sym.bind(cpu(), args, grad_req="null",
                                    aux_states=aux)
        self._outputs = None

    def set_input(self, key, raw):
        if key not in self._input_shapes:
            raise MXNetError(f"predictor: unknown input {key!r}")
        shape = self._input_shapes[key]
        arr = np.frombuffer(raw, np.float32).reshape(shape)
        self._exec.arg_dict[key][:] = arr
        return True

    def forward(self):
        self._outputs = self._exec.forward(is_train=False)
        return True

    def output_shape(self, index):
        # answer from shape inference — never run the model for a shape
        # query (and never cache zero-input outputs as if they were real)
        if self._outputs is not None:
            return tuple(int(d) for d in self._outputs[int(index)].shape)
        _, out_shapes, _ = self._sym.infer_shape(**self._input_shapes)
        return tuple(int(d) for d in out_shapes[int(index)])

    def output_bytes(self, index):
        if self._outputs is None:
            raise MXNetError("forward() has not run")
        out = self._outputs[int(index)].asnumpy().astype(np.float32)
        return np.ascontiguousarray(out).tobytes()
