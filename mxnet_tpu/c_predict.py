"""Python side of the C predict API (driven by src/c_predict_api.cc).

Parity: the reference's standalone predict ABI (c_predict_api.cc) binds a
symbol + params for inference only; here the Predictor wraps a bound
Executor with grad_req='null'. Params arrive as the raw bytes of a
.params file (nd.save format), inputs/outputs as raw float32 buffers.

Executor acquisition goes through the serving layer's shared compiled-
executor cache, keyed by content hash of (symbol JSON, param bytes) plus
the input-shape signature: a C host that creates a fresh Predictor per
request — the reference deployment pattern — reuses one bound executor
(and its compiled XLA program) instead of rebinding every time.
"""
from __future__ import annotations

import hashlib
import os
import tempfile

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .context import cpu
from .symbol import load_json


def _load_params_bytes(raw):
    """Deserialize nd.save bytes → dict (tolerates arg:/aux: prefixes)."""
    # nd.load reads from a path; spool the bytes through a temp file
    with tempfile.NamedTemporaryFile(suffix=".params", delete=False) as f:
        f.write(raw)
        path = f.name
    try:
        loaded = nd.load(path)
    finally:
        os.unlink(path)
    if not isinstance(loaded, dict):
        raise MXNetError(
            "predictor params must be a NAMED dict save "
            "(nd.save(path, {'arg:name': ...}) / HybridBlock.export); "
            "got an unnamed list save")
    out = {}
    for k, v in loaded.items():
        out[k.split(":", 1)[-1]] = v
    return out


class Predictor:
    def __init__(self, symbol_json, param_bytes, input_shapes):
        from .serving.executor_cache import (bind_inference_executor,
                                             shape_signature, shared_cache)
        self._sym = load_json(symbol_json)
        self._input_shapes = {k: tuple(int(d) for d in v)
                              for k, v in input_shapes.items()}
        # content-addressed identity: same model bytes + same shapes ->
        # same bound executor, across Predictor instances
        key = ("c_predict",
               hashlib.sha1(symbol_json.encode()).hexdigest(),
               hashlib.sha1(param_bytes).hexdigest(),
               shape_signature(self._input_shapes))

        def _bind():
            params = _load_params_bytes(param_bytes)
            return bind_inference_executor(self._sym, params,
                                           self._input_shapes, cpu())

        self._cached = shared_cache().get(key, _bind)
        self._exec = self._cached.executor
        self._outputs = None

    def set_input(self, key, raw):
        if key not in self._input_shapes:
            raise MXNetError(f"predictor: unknown input {key!r}")
        shape = self._input_shapes[key]
        arr = np.frombuffer(raw, np.float32).reshape(shape)
        self._exec.arg_dict[key][:] = arr
        return True

    def forward(self):
        self._outputs = self._exec.forward(is_train=False)
        return True

    def output_shape(self, index):
        # answer from shape inference — never run the model for a shape
        # query (and never cache zero-input outputs as if they were real)
        if self._outputs is not None:
            return tuple(int(d) for d in self._outputs[int(index)].shape)
        _, out_shapes, _ = self._sym.infer_shape(**self._input_shapes)
        return tuple(int(d) for d in out_shapes[int(index)])

    def output_bytes(self, index):
        if self._outputs is None:
            raise MXNetError("forward() has not run")
        out = self._outputs[int(index)].asnumpy().astype(np.float32)
        return np.ascontiguousarray(out).tobytes()
