"""Python side of the C predict API (driven by src/c_predict_api.cc).

Parity: the reference's standalone predict ABI (c_predict_api.cc) binds a
symbol + params for inference only; here the Predictor wraps a bound
Executor with grad_req='null'. Params arrive as the raw bytes of a
.params file (nd.save format), inputs/outputs as raw float32 buffers.

Executor acquisition goes through the serving layer's shared compiled-
executor cache, keyed by content hash of (symbol JSON, param bytes) plus
the input-shape signature: a C host that creates a fresh Predictor per
request — the reference deployment pattern — reuses one bound executor
(and its compiled XLA program) instead of rebinding every time.
"""
from __future__ import annotations

import hashlib
import os
import tempfile

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .context import cpu
from .symbol import load_json


def _load_params_bytes(raw):
    """Deserialize nd.save bytes → dict (tolerates arg:/aux: prefixes)."""
    # nd.load reads from a path; spool the bytes through a temp file
    with tempfile.NamedTemporaryFile(suffix=".params", delete=False) as f:
        f.write(raw)
        path = f.name
    try:
        loaded = nd.load(path)
    finally:
        os.unlink(path)
    if not isinstance(loaded, dict):
        raise MXNetError(
            "predictor params must be a NAMED dict save "
            "(nd.save(path, {'arg:name': ...}) / HybridBlock.export); "
            "got an unnamed list save")
    out = {}
    for k, v in loaded.items():
        out[k.split(":", 1)[-1]] = v
    return out


class Predictor:
    def __init__(self, symbol_json, param_bytes, input_shapes):
        from .serving.executor_cache import (bind_inference_executor,
                                             shape_signature, shared_cache)
        self._sym = load_json(symbol_json)
        self._input_shapes = {k: tuple(int(d) for d in v)
                              for k, v in input_shapes.items()}
        # content-addressed identity: same model bytes + same shapes ->
        # same bound executor, across Predictor instances
        key = ("c_predict",
               hashlib.sha1(symbol_json.encode()).hexdigest(),
               hashlib.sha1(param_bytes).hexdigest(),
               shape_signature(self._input_shapes))

        def _bind():
            params = _load_params_bytes(param_bytes)
            return bind_inference_executor(self._sym, params,
                                           self._input_shapes, cpu())

        self._cached = shared_cache().get(key, _bind)
        self._exec = self._cached.executor
        # the cached executor (and its input/output buffers) is shared
        # with every other live Predictor of the same model+shapes, so
        # inputs are staged per-Predictor here and only written under
        # the executor lock in forward(); zeros mirror the freshly-bound
        # buffer contents for inputs the caller never sets
        self._staged = {k: np.zeros(v, np.float32)
                        for k, v in self._input_shapes.items()}
        self._outputs = None

    def set_input(self, key, raw):
        if key not in self._input_shapes:
            raise MXNetError(f"predictor: unknown input {key!r}")
        shape = self._input_shapes[key]
        self._staged[key] = np.frombuffer(raw, np.float32).reshape(shape) \
            .copy()  # snapshot: the caller may recycle its buffer
        return True

    def forward(self):
        # write-inputs -> forward -> copy-outputs is one atomic critical
        # section: interleaved Predictors sharing this executor must not
        # clobber each other's inputs or read each other's outputs
        with self._cached.lock:
            ex = self._exec
            for key, arr in self._staged.items():
                ex.arg_dict[key][:] = arr
            outs = ex.forward(is_train=False)
            outputs = [np.asarray(o.asnumpy()) for o in outs]
        # per-instance state: assigned outside the executor lock (the
        # lock guards the SHARED bound buffers, nothing of this instance)
        self._outputs = outputs
        return True

    def output_shape(self, index):
        # answer from shape inference — never run the model for a shape
        # query (and never cache zero-input outputs as if they were real)
        if self._outputs is not None:
            return tuple(int(d) for d in self._outputs[int(index)].shape)
        _, out_shapes, _ = self._sym.infer_shape(**self._input_shapes)
        return tuple(int(d) for d in out_shapes[int(index)])

    def output_bytes(self, index):
        if self._outputs is None:
            raise MXNetError("forward() has not run")
        out = self._outputs[int(index)].astype(np.float32)
        return np.ascontiguousarray(out).tobytes()
