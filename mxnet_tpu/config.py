"""Environment-variable configuration tier.

Parity: the reference reads ~79 documented MXNET_* variables via
dmlc::GetEnv at use sites (docs/faq/env_var.md; SURVEY.md §5 config
tiers).  This module is the single typed registry for every variable
the TPU framework consumes: each entry declares type, default, and doc,
``get()`` parses with validation, and ``describe()`` renders the
env_var.md-style table so the surface is discoverable
(mx.config.describe()).

Variables the reference defines but XLA/PJRT makes moot (memory-pool
knobs, engine thread counts, cudnn autotune) are intentionally absent —
XLA owns those decisions; see SURVEY.md §7 architecture stance.
"""
from __future__ import annotations

import os

from .base import MXNetError

_REGISTRY = {}


class _Var:
    __slots__ = ("name", "vtype", "default", "doc")

    def __init__(self, name, vtype, default, doc):
        self.name = name
        self.vtype = vtype
        self.default = default
        self.doc = doc


def _register(name, vtype, default, doc):
    _REGISTRY[name] = _Var(name, vtype, default, doc)


def get(name):
    """Typed value of a registered env var (default when unset)."""
    var = _REGISTRY.get(name)
    if var is None:
        raise MXNetError(f"unknown config variable {name!r}; see "
                         "mxnet_tpu.config.describe()")
    raw = os.environ.get(name)
    if raw is None:
        return var.default
    try:
        if var.vtype is bool:
            low = raw.strip().lower()
            if low in ("1", "true", "yes", "on"):
                return True
            if low in ("0", "false", "no", "off", ""):
                return False
            raise ValueError(raw)
        return var.vtype(raw)
    except (TypeError, ValueError) as e:
        raise MXNetError(
            f"config variable {name}={raw!r} is not a valid "
            f"{var.vtype.__name__}") from e


def list_vars():
    return sorted(_REGISTRY)


def describe():
    """env_var.md-style table of every registered variable."""
    lines = [f"{'Variable':<40}{'Type':<8}{'Default':<18}Description"]
    for name in list_vars():
        v = _REGISTRY[name]
        lines.append(f"{name:<40}{v.vtype.__name__:<8}"
                     f"{str(v.default):<18}{v.doc}")
    return "\n".join(lines)


# -- engine / execution ------------------------------------------------------
_register("MXNET_ENGINE_TYPE", str, "ThreadedEnginePerDevice",
          "NaiveEngine blocks after every op (serial debugging, parity: "
          "src/engine/naive_engine.cc)")
_register("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", int, 15,
          "bulking hint kept for API parity; XLA fuses regardless")
_register("MXNET_BACKWARD_DO_MIRROR", bool, False,
          "rematerialize forward activations during backward (memory for "
          "FLOPs; parity: gradient.cc mirror fn) — TrainStep jax.checkpoint")
_register("MXNET_SUBGRAPH_BACKEND", str, "",
          "graph-rewrite backend applied at bind time (parity: "
          "src/operator/subgraph/; e.g. 'dense_act'); empty disables")
_register("MXNET_NATIVE_IO", bool, True,
          "load the native data-plane library (src/io_native.cc); "
          "0 forces the pure-Python paths")
# -- kvstore / distributed ---------------------------------------------------
_register("MXNET_KVSTORE_AUTH_TOKEN", str, "",
          "HMAC key for dist kvstore frames (REQUIRED for non-loopback "
          "binds)")
_register("MXNET_KVSTORE_ALLOW_INSECURE", bool, False,
          "allow non-loopback kvstore bind without auth token (trusted "
          "networks only)")
_register("MXNET_KVSTORE_MAX_FRAME", int, 1 << 30,
          "maximum kvstore wire frame size in bytes")
_register("MXNET_KVSTORE_HEARTBEAT_INTERVAL", float, 5.0,
          "worker heartbeat period in seconds (0 disables); feeds "
          "get_num_dead_node")
_register("MXNET_KVSTORE_RETRIES", int, 3,
          "bounded retry budget for kvstore client RPCs on transport "
          "failures (reconnect + resend with exponential backoff and "
          "jitter); 0 fails on the first error.  Sync pushes retried "
          "after a lost REPLY are at-least-once — see docs/chaos.md")
_register("MXNET_KVSTORE_RETRY_BACKOFF_S", float, 0.05,
          "base backoff for kvstore client RPC retries; attempt i "
          "sleeps base * 2^i * (1 + jitter)")
_register("MXNET_KVSTORE_PEER_TIMEOUT_S", float, 30.0,
          "kvstore server dead-peer threshold: a rank that has "
          "heartbeated at least once and then goes silent this long is "
          "marked lost, and every in-flight sync pull/barrier that "
          "needs it fails with typed PeerLostError instead of timing "
          "out against a corpse (docs/parallel.md)")
_register("MXNET_OPTIMIZER_AGGREGATION_SIZE", int, 4,
          "weights per aggregated multi_sgd_* dispatch in the SGD "
          "optimizer (0 disables; parity: reference sgd.py)")
_register("MXNET_KVSTORE_BIGARRAY_BOUND", int, 1000000,
          "arrays larger than this many elements are pushed/pulled in "
          "row chunks (parity: kvstore_dist.h:243 key sharding)")
_register("DMLC_ROLE", str, "worker",
          "process role: worker/server (ps-lite contract)")
_register("DMLC_RANK", int, 0, "worker rank")
_register("DMLC_WORKER_ID", int, 0, "alias of DMLC_RANK")
_register("DMLC_NUM_WORKER", int, 1, "number of workers")
_register("DMLC_NUM_SERVER", int, 1, "number of servers (always 1 here)")
_register("DMLC_PS_ROOT_URI", str, "",
          "kvstore server host; empty = single-process degradation")
_register("DMLC_PS_ROOT_PORT", int, 9091, "kvstore server port")
_register("DMLC_PS_BIND_ADDR", str, "127.0.0.1",
          "kvstore server bind address (loopback by default — frames "
          "are pickle)")
# -- multihost runtime -------------------------------------------------------
_register("MXNET_COORDINATOR_URI", str, "",
          "jax.distributed coordinator host for parallel.multihost; "
          "takes precedence over DMLC_PS_ROOT_URI (which is never "
          "borrowed when DMLC_ROLE marks a PS deployment — the PS "
          "socket is not a jax.distributed endpoint)")
_register("MXNET_COORDINATOR_PORT", int, 8476,
          "port for MXNET_COORDINATOR_URI")
# -- data pipeline -----------------------------------------------------------
_register("MXNET_MP_START_METHOD", str, "forkserver",
          "multiprocessing start method for DataLoader worker pools; "
          "'fork' restores zero-pickle datasets but deadlocks once "
          "jax's XLA thread pools are live (gluon/data/dataloader.py)")
# -- fused train step --------------------------------------------------------
_register("MXNET_FUSED_STEP", bool, True,
          "Module train steps: trace forward+backward+optimizer update "
          "into ONE donated jax.jit computation (1 dispatch/step) when "
          "the optimizer exposes fused_update; 0 restores the per-param "
          "dispatch loop (docs/perf_notes.md dispatch overhead)")
_register("MXNET_METRIC_SYNC_INTERVAL", int, 1,
          "Module.update_metric: flush buffered (label, output) pairs "
          "into the metric every N batches instead of forcing a "
          "device->host sync per batch; 1 = sync every batch (exact "
          "legacy behaviour). N>1 requires the data iterator to hand "
          "out fresh label arrays per batch (NDArrayIter does; staged "
          "fit batches always do)")
_register("MXNET_SCAN_STEPS", int, 1,
          "Module.fit: run this many fused train steps as ONE donated "
          "jax.lax.scan dispatch (a K-step window); host control "
          "(metrics, callbacks, watchdog beats) happens at window "
          "boundaries only. 1 = one dispatch per step (PR-4 behaviour); "
          "requires the fused-step eligibility (docs/perf_notes.md)")
_register("MXNET_SCAN_ACCUM", int, 1,
          "in-scan gradient accumulation: each scanned train step "
          "consumes this many micro-batches and applies ONE optimizer "
          "update over their summed gradients (effective batch = "
          "M x bound batch; Module-computed rescale_grad accounts for "
          "it). 1 disables; >1 requires MXNET_SCAN_STEPS mode")
_register("MXNET_MESH_FUSED_STEP", bool, True,
          "Module.fit with an in-process kvstore: trace forward + VJP + "
          "bucketed gradient collectives + optimizer update into ONE "
          "donated shard_map computation per K-step window over the "
          "DeviceMesh (parallel/fused.py), retiring the per-param "
          "push/pull loop from the hot path; 0 keeps the sequential "
          "kvstore loop (docs/parallel.md eligibility matrix)")
_register("MXNET_COLLECTIVE_BUCKET_MB", float, 4.0,
          "mesh fused step: gradients are flattened into buckets of at "
          "most this many MB and reduced with ONE psum/reduce-scatter "
          "per bucket, so XLA can overlap communication with remaining "
          "backward compute instead of issuing one tiny collective per "
          "parameter (docs/parallel.md bucket sizing)")
_register("MXNET_COLLECTIVE_MODE", str, "bucketed",
          "mesh fused step collective formulation: 'bucketed' (default) "
          "or 'off' (skip gradient collectives entirely — WRONG results, "
          "bench/debug only: the differential against 'bucketed' is how "
          "multichip_comm_blocking_pct isolates communication time)")
_register("MXNET_COLLECTIVE_COMPRESSION", str, "none",
          "mesh fused step per-bucket gradient codec: 'none' (exact "
          "dense psum), 'fp16' (halved wire bytes, ~1e-3 relative "
          "tolerance), or '2bit' (error-feedback quantization to "
          "+-threshold/0, packed 4 codes/byte and exchanged with one "
          "all_gather per bucket — 32/R x fewer wire bytes per rank on "
          "an R-way mesh; residuals ride the donated scan carry and "
          "reset at elastic restore).  Changes training numerics: "
          "opt-in, replicated layout only (docs/parallel.md)")
_register("MXNET_COLLECTIVE_COMPRESSION_THRESHOLD", float, 0.5,
          "2bit collective codec emission threshold (parity: reference "
          "gradient_compression kTwoBit default)")
_register("MXNET_MULTIHOST_COORD", str, "",
          "host:port of the jax.distributed coordinator for a "
          "multi-process mesh (empty = single process unless a TPU pod "
          "autodetects); every process of one job must agree")
_register("MXNET_MULTIHOST_NUM_PROCS", int, 1,
          "process count of the multi-host job (1 = single process)")
_register("MXNET_MULTIHOST_PROC_ID", int, 0,
          "this process's rank in the multi-host job")
_register("MXNET_MULTIHOST_CONTROL_URI", str, "",
          "host of the multi-host control-plane kvstore server "
          "(heartbeats, peer states, window rendezvous); empty "
          "disables the liveness layer")
_register("MXNET_MULTIHOST_CONTROL_PORT", int, 0,
          "port of the multi-host control-plane server")
_register("MXNET_MULTIHOST_HEARTBEAT_S", float, 1.0,
          "multi-host runtime heartbeat period to the control server "
          "(0 disables; peers read as lost after "
          "MXNET_MULTIHOST_PEER_TIMEOUT_S of silence)")
_register("MXNET_MULTIHOST_PEER_TIMEOUT_S", float, 10.0,
          "multi-host dead-peer threshold: a rank silent this long is "
          "lost — survivors get typed PeerLostError at the next window "
          "rendezvous/in-flight wait instead of hanging")
_register("MXNET_MULTIHOST_BARRIER_TIMEOUT_S", float, 60.0,
          "deadline for the per-window multi-host rendezvous and for "
          "the survivors' exit barrier; every coordination wait in the "
          "elastic runtime is bounded by a deadline derived from this")
_register("MXNET_MULTIHOST_MAX_RESTARTS", int, 3,
          "elastic launcher: maximum world restarts (preemption "
          "recoveries/resizes) before the job fails typed")
_register("MXNET_FIT_STAGE_NEXT", bool, True,
          "fit loop: stage the NEXT DataBatch host->device "
          "(jax.device_put) while the current step is still in flight, "
          "overlapping input feed with compute; 0 feeds batches "
          "synchronously at forward time")
# -- streaming data plane (io_pipeline.py) -----------------------------------
_register("MXNET_DATA_WORKERS", int, 0,
          "streaming data plane: reader worker threads per "
          "DataPipeline (decode/augment off the train thread) and the "
          "switch for the fit loop's off-thread super-batch assembler; "
          "0 = serial in-thread reads (bitwise-identical batch "
          "sequence, no overlap)")
_register("MXNET_DATA_QUEUE_DEPTH", int, 4,
          "streaming data plane: bounded per-shard output queue depth "
          "(batches); with the in-flight shard window this caps host "
          "RSS — total buffered batches <= depth x max in-flight "
          "shards")
_register("MXNET_DATA_SHARD_SEED", int, 0,
          "streaming data plane: seed for the per-epoch shard order "
          "permutation; the SAME order is produced for any worker "
          "count (the load-bearing determinism contract, docs/data.md)")
# -- fused kernels -----------------------------------------------------------
_register("MXNET_KERNELS", str, "off",
          "kernels subsystem mode: off (legacy per-op gates only), "
          "reference (pure-XLA references, bitwise = off for op paths), "
          "tuned (gated Pallas kernels at the best known config; "
          "reference fallback on gate failure)")
_register("MXNET_KERNELS_OVERRIDES", str, "",
          "per-kernel mode overrides, e.g. "
          "'layernorm=tuned,attention=off'; unlisted kernels follow "
          "MXNET_KERNELS")
_register("MXNET_KERNELS_TUNE_REPEATS", int, 3,
          "autotuner: timed repeats per candidate config (best-of)")
_register("MXNET_KERNELS_TUNE_BUDGET", int, 8,
          "autotuner: max configs measured per (kernel, shape); 0 = "
          "unlimited")
_register("MXNET_FUSED_LAYERNORM", str, "auto",
          "fused Pallas LayerNorm: 1 forces on, 0 forces plain XLA, "
          "auto probes the exact tile config once and falls back on "
          "Mosaic rejection")
# -- test harness ------------------------------------------------------------
_register("MXNET_TEST_EXAMPLES", bool, False,
          "run the full examples/ suite in tests/test_examples.py "
          "(ci/run.sh sets it; tier-1 runs only the fastest example)")
# -- profiler ---------------------------------------------------------------
_register("MXNET_PROFILER_XPLANE_DIR", str, "",
          "directory for jax.profiler xplane traces (TensorBoard/"
          "perfetto); empty disables the device trace")
_register("MXNET_FUSED_SOFTMAX_CE", str, "auto",
          "fused Pallas softmax-cross-entropy kernel: 1 forces on, 0 "
          "forces plain XLA, auto probes the tile config once on TPU")
_register("MXNET_PROFILER_AUTOSTART", bool, False,
          "start the profiler at import (parity: reference "
          "env_var.md MXNET_PROFILER_AUTOSTART)")
_register("MXNET_PROFILER_MODE", str, "",
          "with AUTOSTART: 'all'/'1' also enables profile_all + "
          "profile_api (parity: reference MXNET_PROFILER_MODE)")
# -- chaos / fault injection -------------------------------------------------
_register("MXNET_CHAOS", str, "",
          "failpoint arm spec: ';'-separated "
          "site=action[(value)][:hits=N][:count=M][:prob=P] arms "
          "(actions: raise/delay/wedge/corrupt/kill; docs/chaos.md "
          "grammar + site catalog); empty disables every failpoint "
          "with zero behavior change")
_register("MXNET_CHAOS_SEED", int, 0,
          "seed for the per-site chaos random streams (prob triggers, "
          "corrupt-byte positions) — same spec + same seed replays the "
          "same fault schedule")
_register("MXNET_CHAOS_WEDGE_TIMEOUT_S", float, 60.0,
          "a wedge failpoint left unreleased raises ChaosInjectedError "
          "after this long instead of hanging forever (the no-scenario-"
          "ends-in-a-hang contract)")
# -- soak harness ------------------------------------------------------------
_register("MXNET_SOAK_SECONDS", float, 90.0,
          "chaos.soak harness: wall-clock length of the train + "
          "checkpoint + serving-hot-reload + Poisson-traffic loop "
          "(python -m mxnet_tpu.chaos.soak; --seconds overrides)")
_register("MXNET_SOAK_QPS", float, 40.0,
          "chaos.soak harness: Poisson arrival rate of the serving "
          "traffic generator (req/s)")
_register("MXNET_SOAK_CHAOS", bool, True,
          "chaos.soak harness: arm the seeded benign fault mix "
          "(transient router-dispatch raises the spill path heals, "
          "io-stage and checkpoint-gc delays) while the loop runs; "
          "0 soaks the stack fault-free")
_register("MXNET_SOAK_RSS_SLOPE_MAX", float, 4e6,
          "chaos.soak harness: maximum acceptable RSS leak slope "
          "(bytes/s, least-squares over the sampler window) at soak "
          "exit — above it the soak fails")
# -- telemetry ---------------------------------------------------------------
_register("MXNET_TELEMETRY", bool, False,
          "enable the telemetry span tracer + per-train-step lane "
          "breakdown (telemetry.span / callback.StepTimeline); the "
          "metrics registry, collectors and exporter work regardless — "
          "this knob only arms the timed instrumentation "
          "(docs/observability.md)")
_register("MXNET_TELEMETRY_PORT", int, 0,
          "serve telemetry.prometheus_dump() on "
          "http://127.0.0.1:<port>/metrics (plus /snapshot.json and "
          "/healthz) from a daemon thread; 0 disables the endpoint")
_register("MXNET_WATCHDOG_S", float, 0.0,
          "hang watchdog: when an armed section (fit loop, serving "
          "batcher) makes no progress for this many seconds, dump "
          "all-thread stacks + the telemetry snapshot to stderr and a "
          "mxnet-watchdog-<pid>-<n>.txt file; 0 disables "
          "(docs/observability.md runbook)")
_register("MXNET_WATCHDOG_DIR", str, "",
          "directory for hang-watchdog dump files (empty = cwd)")
_register("MXNET_WATCHDOG_KEEP", int, 8,
          "retention for watchdog stall dumps AND flight-recorder dumps "
          "in their target directory: newest N kept, oldest pruned at "
          "each new dump; 0 keeps everything")
_register("MXNET_TRACE", bool, False,
          "end-to-end tracing: thread a trace context (trace_id + stage "
          "spans) through every serving request (submit -> queue_wait -> "
          "stage -> dispatch -> resolve, surviving spill hops) and every "
          "scanned training window (collect -> stage -> rendezvous -> "
          "dispatch -> boundary_flush); stage durations fan out to the "
          "span sinks and finished traces feed the sampled exemplar "
          "store (docs/observability.md trace taxonomy); the disabled "
          "path is one global check, < 1 us")
_register("MXNET_TRACE_SAMPLE", str, "head=8,tail=64",
          "trace exemplar sampling policy per trace kind: keep the "
          "first `head` finished traces (startup behaviour) plus the "
          "`tail` slowest by end-to-end latency (the p99 outliers you "
          "actually decompose); exemplars surface in "
          "telemetry.snapshot()['trace'] and /snapshot.json")
_register("MXNET_FLIGHT", bool, True,
          "crash flight recorder: a lock-cheap bounded ring of "
          "structured events (sheds, spills, chaos injections, restarts, "
          "rendezvous outcomes, checkpoint commits) recorded at every "
          "subsystem's decision points and dumped atomically on "
          "watchdog fire / typed-fatal error / SIGTERM / chaos kill; "
          "0 reduces every record to one global check (< 1 us)")
_register("MXNET_FLIGHT_RING", int, 1024,
          "flight recorder ring capacity in events (oldest evicted)")
_register("MXNET_FLIGHT_DIR", str, "",
          "directory for flight-recorder dump files "
          "(empty = MXNET_WATCHDOG_DIR, then cwd); the elastic launcher "
          "points each worker generation at its postmortem harvest dir")
_register("MXNET_ALERTS", float, 0.0,
          "in-process SLO alert engine: evaluate the rule pack "
          "(telemetry/alerts.py; default pack codifies the doc alarm "
          "table — watchdog stall, corrupt ckpt, spill storm, shed "
          "burn-rate, retrace ratchet, RSS slope, snapshot staleness) "
          "every this many seconds on a daemon thread; firing "
          "page-severity rules flip /healthz to 503 and every "
          "transition lands in the flight ring + /alerts.json; "
          "0 disables (the disabled tick is one global check, < 1 us)")
_register("MXNET_ALERT_RULES", str, "",
          "extra alert rules appended to the default pack: "
          "';'-separated name=family<op>value[:for=S][:cooldown=S]"
          "[:severity=warn|page][:reduce=sum|max|min]"
          "[:kind=threshold|rate|absence][:window=S] arms "
          "(docs/observability.md rule grammar); a name collision "
          "replaces the default rule")
_register("MXNET_RESOURCE_SAMPLE_S", float, 0.0,
          "host resource sampler: sample RSS / open fds / thread count "
          "/ checkpoint-dir disk usage into a sliding window every this "
          "many seconds (feeds the mxnet_resource_* families and the "
          "least-squares RSS leak-slope estimator the rss_slope alert "
          "rule and the soak harness gate on); 0 disables the thread "
          "(the resources collector still takes one on-demand sample "
          "per scrape)")
_register("MXNET_NUMERICS", str, "off",
          "numerics observatory mode for train windows: 'off' (default; "
          "the boundary check is one global read, < 1 us), 'warn' (log + "
          "flight event + forensic dump on a non-finite or rule-breaching "
          "window, training continues), 'skip' (additionally gate each "
          "poisoned step's update on device — the dynamic loss-scaler "
          "idiom, no extra sync — and continue bit-identically to a "
          "manual skip), 'halt' (raise typed NonFiniteError at the "
          "boundary).  Stats (grad/param norms, update ratio, loss "
          "proxy, per-bucket non-finite counts) are computed INSIDE the "
          "donated jit/shard_map window: dispatches/step unchanged, "
          "weights bitwise-identical to off (docs/observability.md)")
_register("MXNET_NUMERICS_GRAD_NORM_MAX", float, 0.0,
          "numerics host-side rule: a window whose global gradient L2 "
          "norm exceeds this is treated like a non-finite window "
          "(warn/skip-record/halt per MXNET_NUMERICS); 0 disables the "
          "rule (the grad_norm_explosion alert rate-rule still watches "
          "the exported gauge)")
_register("MXNET_NUMERICS_HISTORY", int, 512,
          "numerics observatory: per-step stat entries kept in the "
          "in-process history ring (forensic dumps embed it; "
          "numerics.monitor_summary() reads it)")
_register("MXNET_NUMERICS_DUMP_DIR", str, "",
          "directory for mxnet-numerics-<pid>-<n>.json forensic dumps "
          "(empty = MXNET_FLIGHT_DIR, then MXNET_WATCHDOG_DIR, then "
          "cwd); retention shared with MXNET_WATCHDOG_KEEP")
_register("MXNET_NUMERICS_SERVING", bool, True,
          "serving output-health guard: screen each executed batch's "
          "float outputs and fail requests whose rows contain NaN/Inf "
          "with typed NonFiniteError (bumping "
          "mxnet_numerics_serving_nonfinite_total) instead of serving "
          "them; healthy cohort members still resolve.  0 disables the "
          "screen")
_register("MXNET_FLEET_INTERVAL_S", float, 0.0,
          "cross-rank telemetry aggregation: every rank pushes its "
          "registry snapshot to the control-plane kvstore server this "
          "often so the leader can merge a fleet snapshot "
          "(/fleet.json, rank-labelled Prometheus families; dead ranks "
          "keep their last snapshot tagged state=lost); 0 disables the "
          "reporter (the elastic launcher arms it for its workers)")
_register("MXNET_FLEET_DELTA", bool, True,
          "delta-encode fleet telemetry pushes against the last "
          "server-acked snapshot (unchanged families cost ~0 wire "
          "bytes and ~0 leader merge work; a forgotten baseline "
          "resyncs with one full push); 0 forces every push to carry "
          "the full family snapshot")
_register("MXNET_FLEET_HISTORY", int, 8,
          "elastic world generations of per-rank telemetry the fleet "
          "leader retains and serves in /fleet.json?detail=rank; older "
          "generations are pruned (an absence-safe 'history' "
          "truncation marker appears in the detail view once pruning "
          "happened) so a long-lived leader's scrape size plateaus")
_register("MXNET_FLEET_SIM_RANKS", int, 1000,
          "default synthetic rank count for the in-process fleet "
          "simulator (python -m mxnet_tpu.telemetry.fleet_sim); the "
          "--ranks flag overrides")
_register("MXNET_FLEET_SIM_CYCLES", int, 50,
          "default push cycles per fleet-simulator run (virtualized "
          "time: one cycle = one push interval); the --cycles flag "
          "overrides")
_register("MXNET_FLEET_SIM_SEED", int, 0,
          "base seed for the fleet simulator's per-rank metric-family "
          "generators and anomaly schedule (same seed, same fleet); "
          "the --seed flag overrides")
# -- compilation lifecycle ---------------------------------------------------
_register("MXNET_COMPILE_CACHE", bool, True,
          "persistent XLA compilation artifacts: serving executor-cache "
          "misses, ladder warmup and fused/scanned train-step builds "
          "activate jax's persistent compilation cache so a restarted "
          "process deserializes executables instead of recompiling "
          "(docs/compile.md); 0 keeps every compile in-process only")
_register("MXNET_COMPILE_CACHE_DIR", str, "",
          "root directory for persistent compilation artifacts; "
          "artifacts live under a per-(jax, jaxlib, mxnet_tpu) version "
          "subdirectory so stack upgrades invalidate cleanly; empty = "
          "$XDG_CACHE_HOME/mxnet_tpu/compile")
_register("MXNET_COMPILE_CACHE_MIN_COMPILE_S", float, 1.0,
          "only persist programs whose backend compile took at least "
          "this long (tiny programs recompile cheaper than they "
          "hash+stat); tests/smoke/bench set 0 so toy models persist")
_register("MXNET_COMPILE_CACHE_SALT", str, "",
          "extra salt mixed into the artifact version key (forces a "
          "fresh cache namespace without touching the directory; tests "
          "use it to prove versioned invalidation)")
_register("MXNET_COMPILE_WARMUP", bool, True,
          "AOT-compile a model version's full bucket ladder at publish "
          "time via the repository warm hooks — synchronously BEFORE "
          "the served-version pointer flips on checkpoint hot-reload, "
          "on a background thread after a hot-reload load(); 0 keeps "
          "first-request-pays-compile")
_register("MXNET_COMPILE_LADDER_MAX", int, 8,
          "BucketPlanner budget: max compiled bucket boundaries per "
          "model ladder (each boundary is one compiled program)")
_register("MXNET_COMPILE_PLAN_MIN_SAMPLES", int, 256,
          "formed batches that must be observed before the planner "
          "replaces the power-of-two ladder with a measured one")
# -- serving ----------------------------------------------------------------
_register("MXNET_SERVING_MAX_BATCH", int, 32,
          "DynamicBatcher flush size: a batch runs as soon as this many "
          "requests coalesce (upper bound of the bucketed batch dim)")
_register("MXNET_SERVING_MAX_LATENCY_MS", float, 5.0,
          "DynamicBatcher deadline: a partial batch flushes once the "
          "oldest queued request has waited this long (throughput vs "
          "p99 knob; docs/serving.md)")
_register("MXNET_SERVING_QUEUE_DEPTH", int, 256,
          "bounded serving queue capacity (requests)")
_register("MXNET_SERVING_SHED_WATERMARK", int, 0,
          "queue depth at which submits fail fast with "
          "ServingOverloadError; 0 = at queue capacity")
_register("MXNET_SERVING_NUM_WORKERS", int, 1,
          "batch-execution worker threads per batcher replica (each "
          "worker is a stage/dispatch thread pair: micro-batch N+1 "
          "coalesces and stacks while N executes)")
_register("MXNET_SERVING_REPLICAS", int, 1,
          "DynamicBatcher replicas per model endpoint, behind the "
          "load-aware ReplicaPool router (occupancy x drain-time EWMA "
          "routing, graceful spill, drain-on-removal); 1 = single "
          "batcher (docs/serving.md replica pools)")
_register("MXNET_SERVING_SLO_P99_MS", float, 0.0,
          "SLO admission control: shed (ServingOverloadError) once the "
          "router's PREDICTED p99 — pool occupancy / service-rate EWMA "
          "— exceeds this many ms, so the shed point self-tunes to the "
          "model's measured speed; 0 disables (watermark shedding "
          "still applies per replica)")
_register("MXNET_SERVING_SLO_EWMA_ALPHA", float, 0.2,
          "smoothing factor for the admission controller's service-"
          "rate EWMA (higher = faster adaptation, noisier predictions)")
_register("MXNET_SERVING_TIMEOUT_MS", float, 0.0,
          "default per-request timeout (queued past this -> "
          "RequestTimeoutError); 0 disables")
_register("MXNET_SERVING_WORKER_RESTARTS", int, 8,
          "DynamicBatcher: how many times a crashed batch worker thread "
          "is restarted in place (its in-flight batch fails with a "
          "retryable ServingWorkerError) before the batcher gives up "
          "and fails fast instead of hanging; 0 = never restart")
_register("MXNET_SERVING_EXECUTOR_CACHE", int, 32,
          "LRU capacity of the compiled-executor cache, in (model, "
          "version, bucketed-shape) entries")
_register("MXNET_GENERATION_SLOTS", int, 8,
          "KV-cache slots per generation engine = concurrent sessions "
          "one fixed-shape decode micro-batch serves; a full pool "
          "sheds new sessions typed (docs/serving.md generation)")
_register("MXNET_GENERATION_MAX_LEN", int, 512,
          "generation KV arena length cap (prompt + generated tokens "
          "per session; the decode step's fixed sequence dimension)")
_register("MXNET_GENERATION_PAGE_TOKENS", int, 64,
          "KV-cache page granularity in tokens: session reservations "
          "charge the resource ledger in whole pages, and the prefix "
          "cache stores/hits page-aligned prompt prefixes")
_register("MXNET_GENERATION_KV_BUDGET_MB", int, 64,
          "HBM budget for one engine's committed KV pages; admission "
          "sheds typed (ServingOverloadError) rather than commit past "
          "it — the generation analogue of the queue watermark")
_register("MXNET_GENERATION_PREFIX_CACHE", int, 32,
          "prefix-cache capacity in entries (page-aligned prompt-"
          "prefix activations, LRU, content-hash keyed per model "
          "version); 0 disables prefix reuse")
_register("MXNET_GENERATION_LOOP_RESTARTS", int, 2,
          "how many times a crashed generation loop restarts (active "
          "sessions fail typed-retryable and can resume on a sibling) "
          "before the engine fails fast; 0 = never restart")
_register("MXNET_MODULE_PAD_PARTIAL_PREDICT", bool, True,
          "Module.forward(is_train=False): pad a partial final batch up "
          "to the bound batch and slice outputs, instead of rebinding a "
          "new executor shape (serving-style bucketing on the module "
          "predict path)")
# -- checkpoint --------------------------------------------------------------
_register("MXNET_CKPT_ASYNC", bool, True,
          "CheckpointManager: serialize/fsync on a background writer so "
          "save() blocks the train loop only for the device->host "
          "snapshot; 0 makes every save synchronous")
_register("MXNET_CKPT_KEEP_LAST", int, 5,
          "retention: committed checkpoint steps kept (older steps are "
          "garbage-collected after each commit; 0 keeps everything)")
_register("MXNET_CKPT_KEEP_EVERY", int, 0,
          "retention: additionally keep every Nth step forever "
          "(step %% N == 0); 0 disables")
_register("MXNET_CKPT_VERIFY_ON_LOAD", bool, True,
          "verify per-file sha256 checksums on restore; a mismatch "
          "raises CheckpointCorruptError (auto-latest restores fall "
          "back to the previous committed step)")
_register("MXNET_CKPT_WRITE_DELAY_MS", float, 0.0,
          "test/debug: sleep this long between tensor writes and before "
          "the manifest, widening the step-NNNNNN.tmp window for "
          "crash-during-save tests (ci checkpoint smoke)")
_register("MXNET_CKPT_WATCH_INTERVAL_S", float, 1.0,
          "serving ModelRepository.watch poll period for newly "
          "committed checkpoint steps")
_register("MXNET_CKPT_COMMIT_TIMEOUT_S", float, 60.0,
          "multi-host commit: how long host 0 waits for every host's "
          "shard manifest before failing the save")
# -- driver / bench ---------------------------------------------------------
_register("MX_DRYRUN_TIMEOUT", float, 900.0,
          "subprocess timeout for __graft_entry__.dryrun_multichip")
_register("BENCH_TIME_BUDGET", float, 1200.0, "bench.py wall budget (s)")
_register("BENCH_BATCH", int, 32, "bench.py primary batch size")
_register("BENCH_BATCH2", int, 128,
          "bench.py second MFU point (0 disables)")
_register("BENCH_BATCH3", int, 256,
          "bench.py third MFU point (0 disables)")
_register("BENCH_ITERS", int, 20, "bench.py timed iterations")
_register("BENCH_WARMUP", int, 2, "bench.py warmup iterations")
_register("BENCH_K", int, 8,
          "bench.py steps chained per timed dispatch")
_register("BENCH_DTYPE", str, "bfloat16", "bench.py compute dtype")
_register("BENCH_LOSS", str, "fused",
          "bench.py loss path: 'fused' (Pallas softmax-ce) or 'plain'")
_register("BENCH_INIT_TIMEOUT", float, 300.0,
          "bench.py timeout for model init + first compile (s)")
_register("BENCH_REMAT_FROM_BS", int, 64,
          "bench.py: rematerialize the train step at batch >= this "
          "(0 disables); see MXNET_BACKWARD_DO_MIRROR")
_register("BENCH_CALIB_N", str, "4096,8192",
          "bench.py peak-calibration matmul dimensions "
          "(comma-separated sweep)")
_register("BENCH_CALIB_REPS", int, 40,
          "bench.py peak-calibration chain length per size "
          "(one fori_loop dispatch)")
_register("BENCH_REC_IMAGES", int, 512,
          "tools/bench_pipeline.py synthetic .rec image count")
_register("BENCH_WORKERS", int, 4,
          "tools/bench_pipeline.py DataLoader worker count")
_register("BENCH_B", int, 4,
          "tools/bench_attention.py batch size")
_register("BENCH_SEQS", str, "512,1024,2048",
          "tools/bench_attention.py sequence lengths "
          "(comma-separated sweep)")
_register("BENCH_SERVE", bool, True,
          "bench.py: also measure serving throughput (resnet18 via the "
          "DynamicBatcher under Poisson arrivals)")
_register("BENCH_SERVE_SECONDS", float, 8.0,
          "bench.py serving phase: Poisson measurement window (s)")
_register("BENCH_SERVE_RATE", float, 0.0,
          "bench.py serving phase: Poisson arrival rate (req/s); 0 = "
          "auto (1.2x the closed-loop probe throughput)")
_register("BENCH_SERVE_BATCH", int, 32,
          "bench.py serving phase: DynamicBatcher max_batch_size")
_register("BENCH_SERVE_LATENCY_MS", float, 10.0,
          "bench.py serving phase: DynamicBatcher max_latency_ms")
_register("BENCH_SERVE_SPIKE", bool, True,
          "bench.py: also measure the replica-pool phases "
          "serve_sustained_img_per_sec (pool >= 2x single-batcher "
          "throughput) and serve_spike_p99_ms (p99 under a 10x Poisson "
          "spike <= 3x steady, excess shed typed); pure-host runner, "
          "needs no TPU relay")
_register("BENCH_SERVE_SPIKE_SECONDS", float, 2.0,
          "bench.py spike phase: steady-state window length (s); the "
          "spike window runs half as long at BENCH_SERVE_SPIKE_X the "
          "arrival rate")
_register("BENCH_SERVE_SPIKE_X", float, 10.0,
          "bench.py spike phase: spike arrival-rate multiplier over "
          "the steady-state Poisson rate")
_register("BENCH_SERVE_SPIKE_REPLICAS", int, 4,
          "bench.py spike phase: ReplicaPool size (the >= 2x-vs-single "
          "throughput gate scales with this)")
_register("BENCH_GENERATE", bool, True,
          "bench.py: also measure the generation phases "
          "generate_tokens_per_sec / generate_p99_intertoken_ms "
          "(Poisson session arrivals through a pure-host per-token-"
          "cost engine, relay-proof) plus the shared-prefix "
          "prefix-cache hit-rate gate")
_register("BENCH_GENERATE_SECONDS", float, 2.0,
          "bench.py generation phase: Poisson session-arrival window "
          "(s)")
_register("BENCH_GENERATE_RATE", float, 0.0,
          "bench.py generation phase: Poisson session arrival rate "
          "(sessions/s); 0 = auto-sized from the per-token host cost")
_register("BENCH_GENERATE_TOKENS", int, 32,
          "bench.py generation phase: max_new_tokens per session")
_register("BENCH_KERNELS", bool, True,
          "bench.py: measure the kernel_tuner phases (tuner overhead "
          "seconds + reference-vs-kernel CPU trace counts, relay-proof); "
          "device kernel-latency phases ship relay-armed")
_register("BENCH_FLEET", bool, True,
          "bench.py: run the fleet-scale observability simulator "
          "(telemetry.fleet_sim) at rank=100 and rank=1000 in "
          "subprocesses and gate merge p99 / rollup CPU / summary "
          "scrape size / alert lag / sublinearity (relay-proof, pure "
          "host CPU)")
_register("BENCH_DISPATCH", bool, True,
          "bench.py: measure fused-train-step dispatch phases on the CPU "
          "backend (resnet50_step_dispatches / train_step_ms_bs32); "
          "needs no TPU relay")
_register("BENCH_DISPATCH_STEPS", int, 20,
          "bench.py dispatch phase: timed Module steps for "
          "train_step_ms_bs32")
_register("BENCH_DISPATCH_IMAGE", int, 32,
          "bench.py dispatch phase: ResNet-50 image edge for the "
          "dispatch count (count is shape-independent; small keeps CPU "
          "convs cheap)")
_register("BENCH_DISPATCH_BATCH", int, 4,
          "bench.py dispatch phase: ResNet-50 batch for the dispatch "
          "count")
_register("BENCH_SCAN", bool, True,
          "bench.py: also measure the K-step scanned train window on the "
          "CPU backend (train_step_ms_scan_k<K> / "
          "scan_dispatches_per_step); needs no TPU relay")
_register("BENCH_SCAN_K", int, 8,
          "bench.py scan phase: MXNET_SCAN_STEPS window size")
_register("BENCH_DATA", bool, True,
          "bench.py: also measure the streaming data plane — a K=8 "
          "scan-window fit on a compute-representative model with the "
          "multi-worker pipeline on (data_wait_pct, gated < 5% of "
          "step wall) vs the serial in-thread loop "
          "(data_wait_serial_ratio); pure-host phase, needs no TPU "
          "relay")
_register("BENCH_TELEMETRY", bool, True,
          "bench.py: also measure the disabled-path cost of "
          "telemetry.span (telemetry_disabled_span_ns; the <1us budget "
          "that lets hot loops stay annotated unconditionally)")
_register("BENCH_TRACE", bool, True,
          "bench.py: also measure the disabled-path cost of one "
          "end-to-end trace hook + one flight-recorder record "
          "(trace_disabled_overhead_ns; the <1us budget that lets the "
          "request/window tracing and the event ring stay wired into "
          "hot paths unconditionally)")
_register("BENCH_ALERTS", bool, True,
          "bench.py: also measure the alert/resource observatory "
          "overheads — one evaluation pass over the default rule pack "
          "(alert_tick_overhead_us) and one host resource sample "
          "(resource_sample_overhead_us), both gated < 1 ms, plus the "
          "engine-disabled tick gated < 1 us like span/trace/failpoint")
_register("BENCH_LINT", bool, True,
          "bench.py: also measure graftlint_full_tree_s — one "
          "whole-tree run of the two-phase lint engine (lexical walk + "
          "summary collection + call-graph flow rules) in a fresh "
          "subprocess, gated under the ci/run.sh 15 s wall budget with "
          "the slowest rules named from --timings")
_register("BENCH_NUMERICS", bool, True,
          "bench.py: also measure the numerics observatory — armed "
          "K=8 scanned-window overhead vs off (< 5% step wall, "
          "dispatches/step unchanged) and the disabled boundary-check "
          "path (< 1 us, the span/trace/failpoint bar)")
_register("BENCH_COLD_START", bool, True,
          "bench.py: also measure cold_start_first_request_ms — warm "
          "restart (persistent compile cache) vs cold cache dir, in "
          "fresh subprocesses on the CPU backend; needs no TPU relay")
_register("BENCH_CHAOS", bool, True,
          "bench.py: also measure degraded_p99_ms — serving p99 with "
          "one wedged batcher worker vs healthy (gate: <= 3x healthy "
          "p99 while shedding); pure-host phase, needs no TPU relay")
_register("BENCH_MULTICHIP", bool, True,
          "bench.py: also measure the mesh fused distributed step in a "
          "subprocess forced to an 8-fake-device CPU mesh "
          "(multichip_dispatches_per_step / multichip_comm_blocking_pct; "
          "relay-proof like the other CPU phases)")
_register("BENCH_MULTICHIP_K", int, 8,
          "bench.py multichip phase: MXNET_SCAN_STEPS window size on the "
          "dp=2,tp=2 mesh (the <=(1+eps)/K dispatch gate)")
_register("BENCH_MULTIHOST", bool, True,
          "bench.py: also measure the elastic multi-host runtime — "
          "2 worker processes x 4 fake CPU devices each under the "
          "elastic launcher (multihost_dispatches_per_step, "
          "multihost_recovery_s, collective-compression byte ratio); "
          "relay-proof like the other CPU phases")
_register("BENCH_MULTIHOST_K", int, 8,
          "bench.py multihost phase: MXNET_SCAN_STEPS window size for "
          "the 2-process mesh (the <=(1+eps)/K per-process dispatch "
          "gate)")
_register("BENCH_CKPT", bool, True,
          "bench.py: also measure checkpoint save-blocking time and "
          "restore latency (ckpt_save_blocking_ms / ckpt_restore_s)")
_register("BENCH_CKPT_MB", int, 64,
          "bench.py checkpoint phase: synthetic state size in MB")
