"""2-bit gradient compression with error-feedback residual.

Reference: src/kvstore/gradient_compression.{h,cc,-inl.h} — per element:
residual += grad; emit +threshold (code 1) when residual >= threshold and
subtract it, -threshold (code 2) when residual <= -threshold and add it,
else emit 0 (code 0).  The reference packs 16 codes per float32 word on
the wire; here 4 codes pack per byte (uint8) — same 16x size reduction
for float32 gradients, and the packed buffer is what pickles across the
kvstore socket (kvstore_server.py).

The quantize path is plain NumPy: it runs on the host at the transport
boundary (gradients have already been fetched with asnumpy() for the
wire).  The SPMD/ICI path never uses this — XLA collectives move bf16
gradients over ICI; this exists for the parameter-server transport's
DCN-style bandwidth profile.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError

__all__ = ["GradientCompression", "create"]


class GradientCompression:
    """2-bit quantizer with per-key residual state (kTwoBit parity)."""

    def __init__(self, threshold=0.5):
        threshold = float(threshold)
        if threshold <= 0:
            raise MXNetError("2bit compression threshold must be > 0")
        self.threshold = threshold
        self._residuals = {}      # key -> np.ndarray

    # -- core codec ---------------------------------------------------------
    def quantize(self, key, grad):
        """grad (np.ndarray) -> packed uint8 codes; updates the residual.

        Parity: GradientCompression::Quantize (error feedback lives on the
        pushing worker, gradient_compression-inl.h:67-78).
        """
        grad = np.asarray(grad, dtype=np.float32)
        res = self._residuals.get(key)
        if res is None or res.shape != grad.shape:
            res = np.zeros_like(grad)
        t = np.float32(self.threshold)
        res = res + grad
        pos = res >= t
        neg = res <= -t
        res = (res - pos * t + neg * t).astype(np.float32, copy=False)
        self._residuals[key] = res
        codes = pos.astype(np.uint8) | (neg.astype(np.uint8) << 1)
        flat = codes.ravel()
        pad = (-flat.size) % 4
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.uint8)])
        packed = (flat[0::4] | (flat[1::4] << 2) | (flat[2::4] << 4)
                  | (flat[3::4] << 6))
        return packed

    def dequantize(self, packed, shape, dtype=np.float32):
        """packed uint8 codes -> ±threshold / 0 array of ``shape``."""
        packed = np.asarray(packed, dtype=np.uint8)
        flat = np.empty(packed.size * 4, np.uint8)
        flat[0::4] = packed & 0x3
        flat[1::4] = (packed >> 2) & 0x3
        flat[2::4] = (packed >> 4) & 0x3
        flat[3::4] = (packed >> 6) & 0x3
        n = int(np.prod(shape))
        flat = flat[:n]
        out = np.zeros(n, dtype=dtype)
        out[flat == 1] = self.threshold
        out[flat == 2] = -self.threshold
        return out.reshape(shape)

    # -- wire helpers --------------------------------------------------------
    def encode_push(self, key, grad):
        """The dict that replaces a dense gradient on the wire."""
        grad = np.asarray(grad)
        return {"q2bit": self.quantize(key, grad),
                "shape": tuple(grad.shape),
                "threshold": self.threshold,
                "dtype": str(grad.dtype)}

    @staticmethod
    def decode_push(msg):
        gc = GradientCompression(msg["threshold"])
        return gc.dequantize(msg["q2bit"], msg["shape"],
                             np.dtype(msg["dtype"]))


# -- traced collective codecs (ISSUE 11) -------------------------------------
# The same kTwoBit math as the NumPy path above, expressed in jnp so the
# mesh-fused train step can run the quantize -> exchange -> decode cycle
# INSIDE its donated shard_map program: the collective then moves packed
# uint8 codes (2 bits/element, 4 codes/byte) instead of dense float32 —
# 16x smaller per rank-hop.  Error-feedback residuals are the caller's
# responsibility (they ride the scan carry in parallel/fused.py).

COLLECTIVE_CODECS = ("none", "fp16", "2bit")


def codec_wire_bytes(dense_bytes, n_shards, codec):
    """Per-rank bytes transmitted for ONE gradient exchange under the
    standard ring schedules (host shape arithmetic, never a device op):

    * ``none``  — ring all-reduce of dense float32: 2 * (R-1)/R * B
    * ``fp16``  — same schedule at half width:          (R-1)/R * B
    * ``2bit``  — ring all-gather of packed codes (each rank ships its
      B/16 bytes of codes to the ring): (R-1) * B / 16

    dense/2bit ratio is therefore 32/R — e.g. 4x at R=8, 16x at R=2.
    """
    r = max(1, int(n_shards))
    dense_bytes = int(dense_bytes)
    if codec == "fp16":
        return int(dense_bytes * (r - 1) / r)
    if codec == "2bit":
        return int((r - 1) * dense_bytes / 16)
    return int(2 * dense_bytes * (r - 1) / r)


def quantize_2bit_flat(flat, residual, threshold):
    """Traced kTwoBit quantize of a flat f32 vector with error feedback.

    Returns ``(packed, new_residual)``: ``packed`` is uint8 of length
    ``ceil(n/4)`` (4 two-bit codes per byte, zero-padded), ready for the
    wire; ``new_residual`` keeps what the codes failed to express.
    """
    import jax.numpy as jnp
    t = jnp.float32(threshold)
    acc = residual + flat
    pos = acc >= t
    neg = acc <= -t
    new_res = acc - jnp.where(pos, t, 0.0) + jnp.where(neg, t, 0.0)
    codes = pos.astype(jnp.uint8) | (neg.astype(jnp.uint8) << 1)
    pad = (-codes.shape[0]) % 4
    if pad:
        codes = jnp.concatenate(
            [codes, jnp.zeros((pad,), jnp.uint8)])
    c = codes.reshape(-1, 4)
    packed = (c[:, 0] | (c[:, 1] << 2) | (c[:, 2] << 4) | (c[:, 3] << 6))
    return packed, new_res


def decode_2bit_sum(gathered, threshold, n):
    """Decode an all-gathered ``(R, ceil(n/4))`` packed-code block and
    sum the R ranks' contributions — the compressed equivalent of the
    dense psum's element-wise add (each rank contributes exactly the
    ±threshold/0 values its codes encode)."""
    import jax.numpy as jnp
    t = jnp.float32(threshold)
    p = gathered
    codes = jnp.stack([p & 0x3, (p >> 2) & 0x3, (p >> 4) & 0x3,
                       (p >> 6) & 0x3], axis=-1)
    codes = codes.reshape(gathered.shape[0], -1)[:, :n]
    vals = t * (codes == 1) - t * (codes == 2)
    return jnp.sum(vals.astype(jnp.float32), axis=0)


def create(compression_params):
    """Validate + build from a set_gradient_compression params dict
    (parity: GradientCompression::SetParams)."""
    params = dict(compression_params)
    ctype = params.pop("type", None)
    if ctype in (None, "none"):
        return None
    if ctype != "2bit":
        raise MXNetError(
            f"unsupported gradient compression type {ctype!r} "
            "(supported: '2bit')")
    threshold = params.pop("threshold", 0.5)
    if params:
        raise MXNetError(
            f"unknown gradient compression params: {sorted(params)}")
    return GradientCompression(threshold)
