"""Module API: symbolic training interface.

Re-design of reference python/mxnet/module/ (BaseModule.fit:409, Module:364
over DataParallelExecutorGroup, BucketingModule). Each Module owns one
Executor per context; forward/backward run the whole compiled graph (the
per-node engine pushes + bulking of graph_executor.cc collapse into one XLA
program per signature). Batches bigger than one context are split along the
batch axis (DataParallelExecutorGroup._load_data semantics).
"""
from __future__ import annotations

import logging
import os
import time

import numpy as np

from . import io as mx_io
from . import metric as metric_mod
from . import ndarray as nd
from . import optimizer as opt_mod
from . import telemetry as _telemetry
from .base import (MXNetError, NonFiniteError, PeerLostError,
                   PreemptionError)
from .context import cpu
from .initializer import Uniform
from .model import (BatchEndParam, load_checkpoint, save_checkpoint,
                    _create_kvstore, _initialize_kvstore,
                    _update_params_on_kvstore)
from .ndarray import NDArray


class BaseModule:
    """Base class defining the Module API (parity: module/base_module.py)."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self.inputs_need_grad = False
        self._symbol = None

    # -- high-level train/eval loops ---------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """Train the module (parity: base_module.py:409 fit)."""
        assert num_epoch is not None, "please specify number of epochs"
        if initializer is None:
            initializer = Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        stager = mx_io.make_batch_stager(getattr(self, "_context", None))
        # step-time breakdown (telemetry lanes) + hang watchdog: both are
        # shared no-ops unless MXNET_TELEMETRY / MXNET_WATCHDOG_S arm them
        timeline = _telemetry.step_timer()
        wdog = _telemetry.watchdog
        try:
            self._fit_epochs(train_data, eval_data, eval_metric,
                             validation_metric, monitor, stager, timeline,
                             wdog, epoch_end_callback, batch_end_callback,
                             eval_end_callback, eval_batch_end_callback,
                             begin_epoch, num_epoch)
        except (PeerLostError, PreemptionError) as e:
            # the elastic self-heal hook: a lost peer / preemption
            # notice surfaced at a window boundary — hand the module to
            # the elastic session (boundary checkpoint on the survivor,
            # telemetry) before the typed error propagates to the
            # worker main for the survivor-mesh restore
            from .parallel import elastic as _elastic
            _elastic.on_fit_fault(self, e)
            raise
        finally:
            timeline.close()

    def _fit_epochs(self, train_data, eval_data, eval_metric,
                    validation_metric, monitor, stager, timeline, wdog,
                    epoch_end_callback, batch_end_callback,
                    eval_end_callback, eval_batch_end_callback,
                    begin_epoch, num_epoch):
        """The epoch/batch loop of ``fit`` (instrumented: every loop
        iteration attributes its wall time to telemetry step lanes and
        beats the hang watchdog).  With ``MXNET_SCAN_STEPS``/``_ACCUM``
        the epoch body runs K-step scanned windows instead of per-batch
        steps (one donated XLA dispatch per window; host control only at
        window boundaries) when the module supports it."""
        with wdog.arm("train/fit"):
            for epoch in range(begin_epoch, num_epoch):
                tic = time.time()
                eval_metric.reset()
                plan = self._scan_plan()
                if plan is not None:
                    nbatch = self._fit_epoch_scan(
                        epoch, train_data, eval_metric, plan, stager,
                        timeline, wdog, batch_end_callback)
                else:
                    nbatch = self._fit_epoch_loop(
                        epoch, train_data, eval_metric, monitor, stager,
                        timeline, wdog, batch_end_callback)
                self.flush_metric_updates()
                for name, val in eval_metric.get_name_value():
                    self.logger.info("Epoch[%d] Train-%s=%f", epoch, name,
                                     val)
                toc = time.time()
                # legacy per-epoch log line (reference parity); per-step
                # phases go through telemetry lanes
                cost = toc - tic  # graftlint: disable=raw-phase-timing -- epoch wall is a user log line, not a phase metric
                self.logger.info("Epoch[%d] Time cost=%.3f", epoch, cost)
                arg_params, aux_params = self.get_params()
                self.set_params(arg_params, aux_params)
                if epoch_end_callback is not None:
                    for callback in _as_list(epoch_end_callback):
                        callback(epoch, self.symbol, arg_params, aux_params)
                if eval_data is not None:
                    res = self.score(
                        eval_data, validation_metric,
                        score_end_callback=eval_end_callback,
                        batch_end_callback=eval_batch_end_callback,
                        epoch=epoch)
                    for name, val in res:
                        self.logger.info("Epoch[%d] Validation-%s=%f",
                                         epoch, name, val)
                train_data.reset()
                wdog.beat("train/fit")

    def _fit_epoch_loop(self, epoch, train_data, eval_metric, monitor,
                        stager, timeline, wdog, batch_end_callback):
        """One epoch, one host visit per batch (the pre-scan fit body)."""
        nbatch = 0
        data_iter = iter(train_data)
        end_of_batch = False
        with timeline.lane("data_wait"):
            next_data_batch = next(data_iter)
        if stager is not None:
            with timeline.lane("h2d_stage"):
                next_data_batch = stager(next_data_batch)
        timeline.begin_step()
        while not end_of_batch:
            data_batch = next_data_batch
            if monitor is not None:
                monitor.tic()
            with timeline.lane("step_dispatch"):
                self.forward_backward(data_batch)
            if stager is not None:
                # double-buffer input feed: batch N+1's
                # host->device copy overlaps the step still in
                # flight on batch N (the staged copy also makes
                # buffer-reusing iterators safe to prefetch from
                # before update_metric reads batch N's labels)
                fetched = None
                with timeline.lane("data_wait"):
                    try:
                        fetched = next(data_iter)
                    except StopIteration:
                        end_of_batch = True
                if fetched is not None:
                    with timeline.lane("h2d_stage"):
                        next_data_batch = stager(fetched)
            with timeline.lane("step_dispatch"):
                self.update()
            # device_block/metric_flush lanes are attributed
            # inside update_metric (it knows where the sync is)
            self.update_metric(eval_metric, data_batch.label)
            if stager is None:
                with timeline.lane("data_wait"):
                    try:
                        next_data_batch = next(data_iter)
                    except StopIteration:
                        end_of_batch = True
            if monitor is not None:
                monitor.toc_print()
            if batch_end_callback is not None:
                batch_end_params = BatchEndParam(
                    epoch=epoch, nbatch=nbatch,
                    eval_metric=eval_metric, locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(batch_end_params)
            nbatch += 1
            timeline.end_step()
            wdog.beat("train/fit")
        return nbatch

    def _scan_plan(self):
        """(K, M) when this epoch should run K-step scanned windows with
        M-way in-scan gradient accumulation, else None.  Only Module
        overrides the eligibility; every other module type keeps the
        per-batch loop."""
        return None

    def _fit_epoch_scan(self, epoch, train_data, eval_metric, plan,
                        stager, timeline, wdog, batch_end_callback):
        """One epoch in K-step windows: each full window of K*M
        same-shape batches is staged as one super-batch and dispatched
        as ONE scanned XLA computation; metrics, callbacks, watchdog
        beats and timeline accounting happen at window boundaries.
        Batches that don't fill a window (epoch tail, shape-mismatched
        batches) and windows after a scan-trace failure run through the
        per-batch path unchanged."""
        K, M = plan[0], plan[1]
        W = K * M
        # a healthy window legitimately goes W batch-times between
        # beats: scale the watchdog deadline so K=32 runs stay silent
        # while real wedges still fire
        wdog.set_scale("train/fit", W)
        _telemetry.record_scan_window(K)
        try:
            return self._fit_epoch_scan_inner(
                epoch, train_data, eval_metric, plan, stager, timeline,
                wdog, batch_end_callback)
        finally:
            wdog.set_scale("train/fit", 1)

    def _fit_epoch_scan_inner(self, epoch, train_data, eval_metric, plan,
                              stager, timeline, wdog, batch_end_callback):
        K, M = plan[0], plan[1]
        W = K * M
        ctx = getattr(self, "_context", None)
        # a mesh window re-places its stacked feeds itself
        # (DeviceMesh.put_batch shards the batch axis), so stage the
        # super-batch host-side there — one placement, not two
        stage_host = len(plan) > 2 and plan[2] is not None
        data_iter = iter(train_data)
        state = {"exhausted": False}
        nbatch = 0
        from . import io_pipeline as mx_pipe
        feed = None
        if mx_pipe.feed_enabled():
            # streaming data plane (ISSUE 19): collect AND stage the
            # next window off the train thread, double-buffered — the
            # stage/dispatch thread-pair idiom applied to input.  The
            # train thread only blocks in feed.get(), charged to the
            # data_wait lane; a wedged feed stops the train/fit beats,
            # so the watchdog still pages.
            feed = mx_pipe.WindowFeed(data_iter, W, ctx,
                                      self._scan_batch_ok,
                                      host=stage_host)

        def collect():
            # the next W same-shape batches (+ their pre-staged
            # super-batch when the window feed is on); shorter on epoch
            # end or when a shape-mismatched batch (tail partial,
            # bucketing) shows up — those route through the per-batch
            # path in arrival order
            if feed is not None:
                with timeline.lane("data_wait"):
                    kind, payload, sbatch, span = feed.get()
                if kind == "end":
                    state["exhausted"] = True
                    state["collect"] = None
                    return [], [], None
                state["collect"] = span
                if kind == "window":
                    return payload, [], sbatch
                return payload, [], None
            t_c0 = time.perf_counter()
            batches, tail = [], []
            while len(batches) < W:
                with timeline.lane("data_wait"):
                    try:
                        b = next(data_iter)
                    except StopIteration:
                        state["exhausted"] = True
                        break
                if not self._scan_batch_ok(b):
                    tail.append(b)
                    break
                batches.append(b)
            # the interval the NEXT window's trace claims as its
            # "collect" stage (prefetched collects belong to the window
            # they feed, not the one in flight while they ran)
            state["collect"] = (t_c0, time.perf_counter())
            return batches, tail, None

        def per_batch(batch):
            nonlocal nbatch
            if stager is not None:
                with timeline.lane("h2d_stage"):
                    batch = stager(batch)
            with timeline.lane("step_dispatch"):
                self.forward_backward(batch)
                self.update()
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                batch_end_params = BatchEndParam(
                    epoch=epoch, nbatch=nbatch, eval_metric=eval_metric,
                    locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(batch_end_params)
            nbatch += 1
            timeline.end_step()
            wdog.beat("train/fit")

        pending = collect()
        timeline.begin_step()
        try:
            while True:
                batches, tail, staged = pending
                is_window = (staged is not None) if feed is not None \
                    else (len(batches) == W)
                outs = False
                wtrace = _telemetry.trace.NULL_TRACE
                if is_window and not self._scan_disabled:
                    # the SIGKILL-mid-scan-window scenario arms a kill
                    # here: deterministically between the last boundary's
                    # host control and the next window's dispatch
                    from .chaos.failpoints import failpoint as _chaos_fp
                    _chaos_fp("train/scan_window")
                    # window trace (ISSUE 12): collect -> stage ->
                    # [rendezvous, recorded by the multi-host step via the
                    # ambient trace] -> dispatch -> boundary_flush
                    wtrace = _telemetry.trace.start("train", "fit/window")
                    wtrace.add_stage(
                        "collect", *(state.get("collect")
                                     or (wtrace.t0, wtrace.t0)))
                    if staged is not None:
                        # the window feed already collected AND staged
                        # this super-batch off-thread — zero train-thread
                        # staging time (that is the point)
                        sbatch = staged
                    else:
                        with timeline.lane("h2d_stage"), \
                                wtrace.stage("stage"):
                            sbatch = mx_io.stage_super_batch(
                                batches, ctx, host=stage_host)
                    _telemetry.trace.set_current(wtrace)
                    try:
                        with timeline.lane("step_dispatch"), \
                                wtrace.stage("dispatch"):
                            outs = self._run_scan_window(sbatch, plan)
                    except (PeerLostError, PreemptionError) as e:
                        # elastic events are NOT trace failures: a lost
                        # peer or a preemption notice must reach the
                        # elastic session (boundary checkpoint +
                        # survivor-mesh restore), never degrade into
                        # per-batch steps
                        wtrace.event("elastic_fault",
                                     cause=type(e).__name__)
                        wtrace.finish(status="elastic_fault")
                        raise
                    except NonFiniteError:
                        # numerics halt (MXNET_NUMERICS=halt) is a
                        # verdict, not a trace failure: propagate typed to
                        # the caller — never degrade into per-batch steps
                        # that would keep training on the poisoned carry
                        wtrace.event("nonfinite_halt")
                        wtrace.finish(status="nonfinite")
                        raise
                    except Exception as e:  # trace failure: fall back
                        self.logger.warning(
                            "scanned train window disabled (%s: %s); "
                            "falling back to per-batch steps%s",
                            type(e).__name__, e,
                            " — MXNET_SCAN_ACCUM gradient accumulation "
                            "is LOST on the fallback path" if M > 1
                            else "")
                        self._scan_disabled = True
                        self._scan = None
                        # NOTE: self._mesh stays set — it records that
                        # the mesh path engaged this fit (scenario
                        # evidence); _scan_disabled prevents re-entry
                    finally:
                        _telemetry.trace.set_current(None)
                if outs is not False:
                    # prefetch: collect the next window while this scan
                    # is still in flight on device (dispatch was async)
                    pending = collect()
                    # window boundary: the only host-control point —
                    # metric updates (stacked, one sync), batch
                    # callbacks, timeline, watchdog beat
                    with wtrace.stage("boundary_flush"):
                        self._window_update_metrics(eval_metric, sbatch,
                                                    outs)
                        if batch_end_callback is not None:
                            for j in range(W):
                                batch_end_params = BatchEndParam(
                                    epoch=epoch, nbatch=nbatch + j,
                                    eval_metric=eval_metric,
                                    locals=locals())
                                for callback in \
                                        _as_list(batch_end_callback):
                                    callback(batch_end_params)
                    wtrace.finish()
                    nbatch += W
                    timeline.end_step(steps=W)
                    wdog.beat("train/fit")
                    continue
                wtrace.finish(status="fallback")
                for b in batches:
                    per_batch(b)
                for b in tail:
                    per_batch(b)
                if state["exhausted"]:
                    break
                pending = collect()
        finally:
            if feed is not None:
                feed.close()
        return nbatch

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Evaluate (parity: base_module.py score)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                batch_end_params = BatchEndParam(
                    epoch=epoch, nbatch=nbatch, eval_metric=eval_metric,
                    locals=locals())
                for callback in _as_list(batch_end_callback):
                    callback(batch_end_params)
            actual_num_batch += 1
        self.flush_metric_updates()
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """Run prediction, collect outputs (parity: base_module.py predict)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out.slice_axis(0, 0, out.shape[0] - (pad or 0))
                       for out in self.get_outputs()]
            output_list.append(outputs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs
            output_list2 = [nd.concat(*[out[i] for out in output_list], dim=0)
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    # -- interface subclasses implement ------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def bind(self, *args, **kwargs):
        raise NotImplementedError()

    def init_params(self, *args, **kwargs):
        raise NotImplementedError()

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    def flush_metric_updates(self):
        """Drain metric updates buffered under MXNET_METRIC_SYNC_INTERVAL
        (no-op for modules that sync every batch)."""

    def install_monitor(self, mon):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]


def _block_on_maps(*maps):
    """Block until every device array in the maps is ready.  Telemetry's
    ``device_block`` lane wraps this wait explicitly, so the metric math
    that follows reads as pure host time (deferred device errors surface
    here instead of inside the metric — same user-visible sync point)."""
    import jax
    bufs = [v._data for m in maps for v in m.values()
            if isinstance(v, NDArray)]
    if bufs:
        jax.block_until_ready(bufs)


class Module(BaseModule):
    """Module over (symbol, data_names, label_names)
    (parity: module/module.py:364)."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = cpu()
        if isinstance(context, (list, tuple)):
            context = context[0]  # one XLA program covers the device set
        self._context = context
        self._symbol = symbol
        self._data_names = list(data_names) if data_names else []
        self._label_names = list(label_names) if label_names else []
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._arg_params = None
        self._aux_params = None
        self._optimizer = None
        self._kvstore = None
        self._updater = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._monitor = None
        self._fused = None
        self._fused_step_done = False
        self._fused_disabled = False
        self._scan = None
        self._scan_disabled = False
        self._mesh = None          # DeviceMesh when the mesh path engaged
        self._mesh_disabled = False
        self._mesh_local_rows = None  # multi-process: this host's batch rows
        self._auto_mesh = None     # cached all-device dp mesh (False = n/a)
        self._batch_outs_ok = {}   # mesh eligibility: outputs carry batch
        self._zero_buf_cache = {}
        self._pending_metric = []

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create a Module from a checkpoint (parity: module.py load)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = f"{prefix}-{epoch:04d}.states"
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        manager=None):
        """Save symbol + params (+ optimizer states)
        (parity: module.py save_checkpoint).

        With ``manager`` (a checkpoint.CheckpointManager), the save
        routes through the async/atomic subsystem instead — params +
        optimizer state + step land in a committed ``step-NNNN/`` dir,
        and the manager's ``legacy_prefix`` mirror (when configured)
        keeps the ``prefix-NNNN.params`` files readable."""
        if manager is not None:
            return manager.save_module(
                self, epoch, save_optimizer_states=save_optimizer_states,
                epoch=epoch)
        self._sync_params_from_exec()
        save_checkpoint(prefix, epoch, self.symbol, self._arg_params,
                        self._aux_params)
        if save_optimizer_states:
            states = self.get_optimizer_states()
            if states is not None:
                fname = f"{prefix}-{epoch:04d}.states"
                tmp = f"{fname}.tmp-{os.getpid()}"
                with open(tmp, "wb") as f:
                    f.write(states)
                os.replace(tmp, fname)

    def get_optimizer_states(self, dump_optimizer=False):
        """Optimizer state as bytes (None when nothing to save).  Under
        update_on_kvstore the real state lives IN the store (server-side
        for dist) — the local updater never ran — so it is fetched from
        there (parity: module.py save_optimizer_states)."""
        if getattr(self, "_update_on_kvstore", False) and \
                self._kvstore is not None:
            return self._kvstore.get_optimizer_states(dump_optimizer)
        if self._updater is not None:
            return self._updater.get_states(dump_optimizer)
        return None

    def set_optimizer_states(self, states):
        """Install optimizer state bytes (inverse of
        ``get_optimizer_states``); requires init_optimizer first."""
        assert self.optimizer_initialized, \
            "call init_optimizer before restoring optimizer states"
        if getattr(self, "_update_on_kvstore", False) and \
                self._kvstore is not None:
            self._kvstore.set_optimizer_states(states)
        else:
            self._updater.set_states(states)

    # -- bind / params -----------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """Allocate executors (parity: module.py bind → GraphExecutor)."""
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        self._data_shapes = [_as_data_desc(x) for x in data_shapes]
        self._label_shapes = [_as_data_desc(x) for x in label_shapes] \
            if label_shapes else []
        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        for l in self._label_shapes:
            shape_kwargs[l.name] = l.shape
        grad_req_dict = {}
        for name in self.symbol.list_arguments():
            if name in self._data_names:
                grad_req_dict[name] = "write" if inputs_need_grad else "null"
            elif name in self._label_names or name in self._fixed_param_names \
                    or name in self._state_names or not for_training:
                grad_req_dict[name] = "null"
            else:
                grad_req_dict[name] = grad_req
        self._exec = self.symbol.simple_bind(self._context,
                                             grad_req=grad_req_dict,
                                             **shape_kwargs)
        self._fused = None  # new executor: the fused step must re-trace
        self._scan = None
        if self._arg_params is not None:
            self._exec.copy_params_from(self._arg_params, self._aux_params,
                                        allow_extra_params=True)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        """Initialize parameters (parity: module.py init_params)."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing the parameters"
        if initializer is None:
            initializer = Uniform(0.01)
        from .initializer import InitDesc
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr[:] = arg_params[name]
            elif self._arg_params is not None and name in self._arg_params \
                    and not force_init:
                arr[:] = self._arg_params[name]
            else:
                if initializer is None and not allow_missing:
                    raise MXNetError(f"no initializer for {name}")
                initializer(InitDesc(name), arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr[:] = aux_params[name]
            elif self._aux_params is not None and name in self._aux_params \
                    and not force_init:
                arr[:] = self._aux_params[name]
            else:
                initializer(InitDesc(name), arr)
        for name in self._state_names:
            # initial states (RNN hidden/cell): zeros until set_states
            self._exec.arg_dict[name][:] = 0
        self._sync_params_from_exec()
        self.params_initialized = True

    def set_states(self, states=None, value=None):
        """Set value of states (parity: module.py set_states). ``states``
        is a list of NDArrays ordered like state_names, or ``value`` is a
        scalar broadcast to every state. Exactly one must be given."""
        assert self.binded and self._state_names
        if (states is None) == (value is None):
            raise MXNetError(
                "set_states takes exactly one of states= or value=")
        if states is not None:
            if len(states) != len(self._state_names):
                raise MXNetError(
                    f"set_states got {len(states)} arrays for "
                    f"{len(self._state_names)} states {self._state_names}")
            for name, arr in zip(self._state_names, states):
                self._exec.arg_dict[name][:] = arr
        else:
            for name in self._state_names:
                self._exec.arg_dict[name][:] = value

    def get_states(self, merge_multi_context=True):
        assert self.binded and self._state_names
        return [self._exec.arg_dict[n].copy() for n in self._state_names]

    def get_params(self):
        """(arg_params, aux_params) on cpu (parity: module.py get_params)."""
        assert self.binded and self.params_initialized
        self._sync_params_from_exec()
        return self._arg_params, self._aux_params

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def _sync_params_from_exec(self):
        if self._exec is None:
            return
        self._arg_params = {n: self._exec.arg_dict[n].copy()
                            for n in self._param_names}
        self._aux_params = {n: self._exec.aux_dict[n].copy()
                            for n in self._aux_names}

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Install optimizer (parity: module.py init_optimizer →
        model.py _create_kvstore/_initialize_kvstore). A dist kvstore
        synchronizes gradients across workers in update(); the optimizer
        then runs server-side (update_on_kvstore)."""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            opt_kw = dict(optimizer_params or ())
            # loss-layer ops (SoftmaxOutput, *RegressionOutput) emit
            # batch-SUMMED gradients; the optimizer normalizes
            # (parity: module.py:503-506 — and a dist_sync server SUMS
            # worker pushes before updating, so the divisor is the
            # GLOBAL batch)
            if "rescale_grad" not in opt_kw and self._data_shapes:
                batch = self._data_shapes[0][1][0]
                kv_type = kvstore if isinstance(kvstore, str) else \
                    getattr(kvstore, "type", "")
                if "dist" in (kv_type or "") and "_async" not in kv_type:
                    nw = kvstore.num_workers if not isinstance(kvstore, str) \
                        else int(os.environ.get("DMLC_NUM_WORKER", 1))
                    batch *= nw
                # in-scan gradient accumulation sums M micro-batch
                # gradients per update: the divisor is the EFFECTIVE
                # batch, same precedent as the dist global batch above
                from . import config as _config
                batch *= max(1, int(_config.get("MXNET_SCAN_ACCUM")))
                if batch:
                    opt_kw["rescale_grad"] = 1.0 / batch
            optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, **opt_kw)
        elif getattr(optimizer, "rescale_grad", 1.0) == 1.0 and \
                self._data_shapes and self._data_shapes[0][1][0] > 1:
            self.logger.warning(
                "Optimizer created manually outside Module but rescale_grad "
                "= 1.0. Is this intended? (gradients from loss layers are "
                "batch-summed; consider rescale_grad=1/batch_size)")
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        self._fused = None  # optimizer changed: invalidate the fused trace
        self._fused_disabled = False
        self._scan = None
        self._scan_disabled = False
        self._mesh = None
        self._mesh_disabled = False
        arg_params = {n: self._exec.arg_dict[n] for n in self._param_names}
        kv, update_on_kvstore = _create_kvstore(kvstore, 1, arg_params)
        self._kvstore = kv
        self._update_on_kvstore = bool(kv is not None and update_on_kvstore)
        if kv is not None:
            _initialize_kvstore(
                kv, [[arg_params[n]] for n in self._param_names],
                arg_params, self._param_names, self._update_on_kvstore)
            if self._update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        self.optimizer_initialized = True
        if hasattr(self, "_preload_opt_states"):
            if self._update_on_kvstore and kv is not None:
                kv.load_optimizer_states(self._preload_opt_states)
            else:
                with open(self._preload_opt_states, "rb") as f:
                    self._updater.set_states(f.read())
            del self._preload_opt_states
        if hasattr(self, "_preload_opt_states_bytes"):
            # checkpoint.CheckpointManager.restore_module stashes the
            # optimizer blob here; it can only be applied once the
            # updater/kvstore exists
            self.set_optimizer_states(self._preload_opt_states_bytes)
            del self._preload_opt_states_bytes

    # -- compute -----------------------------------------------------------
    def _demesh_arrays(self):
        """Re-place parameter/optimizer-state buffers held as
        mesh-replicated ``jax.Array``s back onto the module's single
        context device.  After mesh-fused windows ran (parallel/
        fused.py), ``arg_dict``/``Updater.states`` hold multi-device
        arrays; the plain executor path (per-batch fallback steps,
        score/predict, direct forward) jits against the context device
        and would fail with incompatible-devices — this collapse runs
        once at the first such use, then the flag re-arms on the next
        mesh window."""
        if not getattr(self, "_mesh_arrays_live", False):
            return
        self._mesh_arrays_live = False
        import jax as _jax
        dev = self._context.jax_device

        def _fix(nd_arr):
            buf = getattr(nd_arr, "_data", None)
            if buf is not None and len(buf.devices()) > 1:
                nd_arr._set_data(_jax.device_put(buf, dev))

        for n in self._param_names:
            _fix(self._exec.arg_dict[n])
        for n in self._aux_names:
            _fix(self._exec.aux_dict[n])
        if self._updater is not None:
            def _walk(s):
                if isinstance(s, (tuple, list)):
                    for t in s:
                        _walk(t)
                elif isinstance(s, NDArray):
                    _fix(s)
            for s in self._updater.states.values():
                _walk(s)
        # the fused-step ownership ledgers point at the old buffers now
        if self._scan is not None:
            self._scan._owned = {}
        if self._fused is not None:
            self._fused._owned = {}

    def forward(self, data_batch, is_train=None):
        """Forward (parity: module.py forward; batch feeds the executor)."""
        assert self.binded and self.params_initialized
        self._demesh_arrays()
        if is_train is None:
            is_train = self.for_training
        # a manual forward supersedes any fused step still pending its
        # update() no-op: the next update() must run the loop
        self._fused_step_done = False
        feed = {}
        for desc, arr in zip(self._data_shapes, data_batch.data):
            feed[desc.name] = arr
        if self._label_shapes and data_batch.label:
            for desc, arr in zip(self._label_shapes, data_batch.label):
                feed[desc.name] = arr
        self._forward_pad = 0
        mismatch = any(
            tuple(arr.shape) != tuple(self._exec.arg_dict[name].shape)
            for name, arr in feed.items())
        if mismatch:
            pad = self._partial_batch_pad(feed) if not is_train else None
            if pad is not None:
                # serving-style bucketing on the predict path: a partial
                # final batch is zero-padded up to the bound batch and the
                # outputs sliced (get_outputs), reusing the compiled
                # program instead of rebinding a new executor shape
                # (MXNET_MODULE_PAD_PARTIAL_PREDICT; docs/serving.md)
                n, bound = pad
                self._forward_pad = bound - n
                self._pad_bound = bound
                self._pad_batch_outputs = self._infer_batch_outputs(
                    feed, n, bound)
                for name, arr in feed.items():
                    # one transfer per INPUT TENSOR: zero-padding the
                    # partial final batch requires the host copy anyway
                    # graftlint: disable=host-sync-in-hot-path -- per-input pad copy, once per partial batch
                    host = arr.asnumpy()
                    host = np.concatenate(
                        [host, np.zeros((bound - n,) + host.shape[1:],
                                        host.dtype)], axis=0)
                    self._exec.arg_dict[name][:] = host
                self._exec.forward(is_train=False)
                return
            # shape change (bucketing / train-mode partial batch):
            # reshape.  The module owns its data arrays, so growing back
            # to the full batch after a partial one is expected — opt
            # into both relaxations explicitly
            self._exec = self._exec.reshape(
                partial_shaping=True, allow_up_sizing=True,
                **{n: a.shape for n, a in feed.items()})
        for name, arr in feed.items():
            self._exec.arg_dict[name][:] = arr
        self._exec.forward(is_train=is_train)

    def _partial_batch_pad(self, feed):
        """(n, bound) when ``feed`` is the bound shapes short a few batch
        rows (pad-and-slice eligible), else None."""
        from . import config as _config
        if not _config.get("MXNET_MODULE_PAD_PARTIAL_PREDICT"):
            return None
        ns, bounds = set(), set()
        for name, arr in feed.items():
            tgt = self._exec.arg_dict[name]
            if tuple(arr.shape[1:]) != tuple(tgt.shape[1:]):
                return None
            ns.add(int(arr.shape[0]))
            bounds.add(int(tgt.shape[0]))
        if len(ns) != 1 or len(bounds) != 1:
            return None
        n, bound = ns.pop(), bounds.pop()
        return (n, bound) if 0 < n < bound else None

    def _infer_batch_outputs(self, feed, n, bound):
        """Which output indices actually carry the padded batch dim —
        exact, by inferring output shapes at batch ``n`` vs ``bound``
        (every non-feed argument keeps its bound shape): only outputs
        whose leading dim tracks the batch get pad-sliced.  Returns
        None when inference cannot decide (get_outputs then falls back
        to the leading-dim heuristic)."""
        cache = getattr(self, "_batch_out_cache", None)
        if cache is None:
            cache = self._batch_out_cache = {}
        key = (n, bound)
        if key not in cache:
            try:
                fixed = {name: tuple(a.shape)
                         for name, a in self._exec.arg_dict.items()
                         if name not in feed}
                fixed.update({name: tuple(a.shape) for name, a
                              in getattr(self._exec, "aux_dict",
                                         {}).items()})

                def outs_at(b):
                    shapes = dict(fixed)
                    shapes.update({name: (b,) + tuple(arr.shape[1:])
                                   for name, arr in feed.items()})
                    _, outs, _ = self.symbol.infer_shape_partial(**shapes)
                    return outs

                outs_n, outs_b = outs_at(n), outs_at(bound)
                if (len(outs_n) == len(outs_b)
                        and all(s is not None for s in outs_n)
                        and all(s is not None for s in outs_b)):
                    cache[key] = frozenset(
                        i for i, (sn, sb) in enumerate(zip(outs_n, outs_b))
                        if sn and sb and sn[0] == n and sb[0] == bound)
                else:
                    cache[key] = None
            except Exception as e:  # noqa: BLE001 — fall back to heuristic
                self.logger.debug(
                    "pad-slice output inference failed (%s: %s); falling "
                    "back to slicing every output", type(e).__name__, e)
                cache[key] = None
        return cache[key]

    def backward(self, out_grads=None):
        """Backward (parity: module.py backward)."""
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """Forward + backward; when the setup is eligible this runs the
        FUSED step instead — forward + VJP + optimizer update as one
        donated XLA dispatch (fused_step.py) — and the following
        ``update()`` becomes a no-op."""
        if self._maybe_fused_step(data_batch):
            return
        self.forward(data_batch, is_train=True)
        self.backward()

    def _fused_eligible(self):
        from . import config as _config
        if not _config.get("MXNET_FUSED_STEP") or self._fused_disabled:
            return False
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized and self.for_training):
            return False
        if getattr(self, "_kvstore", None) is not None:
            return False  # grads must sync/update through the store
        if self.inputs_need_grad or self._monitor is not None:
            return False
        ex = self._exec
        if ex is None or ex._grouped is not None or \
                ex._monitor_callback is not None:
            return False
        if not callable(getattr(self._optimizer, "fused_update", None)):
            return False  # custom optimizer: per-param loop, silently
        if any(ex.grad_req.get(n, "null") not in ("write", "null")
               for n in ex._arg_names):
            return False  # "add" accumulation needs live grad buffers
        return True

    def _maybe_fused_step(self, data_batch):
        if not self._fused_eligible():
            return False
        fs = self._fused
        if fs is None or fs.stale(self):
            from .fused_step import FusedTrainStep
            fs = self._fused = FusedTrainStep(self)
        try:
            ran = fs.step(data_batch)
        except NonFiniteError:
            # the numerics halt verdict (MXNET_NUMERICS=halt) must reach
            # the caller typed — falling back to the per-param loop
            # would keep training through the poison it just caught
            raise
        except Exception as e:  # trace-time failure: fall back for good
            self.logger.warning(
                "fused train step disabled (%s: %s); falling back to the "
                "per-param update loop", type(e).__name__, e)
            self._fused_disabled = True
            self._fused = None
            return False
        if ran:
            self._fused_step_done = True
        return ran

    # -- mesh-fused distributed windows (parallel/fused.py) ----------------
    def _fit_mesh(self):
        """The DeviceMesh the mesh-fused fit path would run on: the
        ambient ``with mesh:`` mesh when one is active, else a cached
        all-device dp mesh (every mesh axis is data-parallel for a
        symbolic Module graph; docs/parallel.md)."""
        from .parallel import current_mesh
        m = current_mesh()
        if m is not None:
            return m
        if self._auto_mesh is None:
            import jax
            from .parallel.mesh import DeviceMesh
            devs = jax.devices()
            self._auto_mesh = DeviceMesh({"dp": len(devs)}, devs) \
                if len(devs) > 1 else False
        return self._auto_mesh or None

    def _mesh_batch_outputs_ok(self, n_shards, batch):
        """Every graph output must carry the batch on its leading dim
        (the window's out_specs shard/unshard dim0): infer output shapes
        at the bound batch AND at the per-shard batch and require dim0
        to track both.  Cached per (n_shards, batch)."""
        key = (n_shards, batch)
        if key not in self._batch_outs_ok:
            try:
                known = {d.name: d.shape for d in self._data_shapes}
                for l in (self._label_shapes or []):
                    known[l.name] = l.shape
                _, outs_b, _ = self.symbol.infer_shape_partial(**known)
                local = {k: (v[0] // n_shards,) + tuple(v[1:])
                         for k, v in known.items()}
                _, outs_s, _ = self.symbol.infer_shape_partial(**local)
                ok = bool(outs_b and outs_s
                          and all(o and o[0] == batch for o in outs_b)
                          and all(o and o[0] == batch // n_shards
                                  for o in outs_s))
            except Exception as e:  # noqa: BLE001 — ineligible, not fatal
                self.logger.debug(
                    "mesh batch-output inference failed (%s: %s); "
                    "keeping the per-param kvstore loop",
                    type(e).__name__, e)
                ok = False
            self._batch_outs_ok[key] = ok
        return self._batch_outs_ok[key]

    def _mesh_fused_eligible(self):
        """True when fit can trace forward + VJP + bucketed gradient
        collectives + optimizer update into one donated shard_map window
        per K steps (parallel/fused.MeshFusedTrainStep) instead of the
        per-param kvstore push/pull loop.  See docs/parallel.md for the
        full eligibility matrix."""
        from . import config as _config
        if not _config.get("MXNET_MESH_FUSED_STEP") or self._mesh_disabled:
            return False
        kv = getattr(self, "_kvstore", None)
        if kv is None or not getattr(kv, "mesh_fusible", False):
            return False  # no store, or a store the mesh cannot absorb
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized and self.for_training):
            return False
        if self.inputs_need_grad or self._monitor is not None:
            return False
        if self._aux_names:
            # per-replica aux mutation (BN running stats) would need
            # sync-BN; the loop path keeps reference semantics
            return False
        ex = self._exec
        if ex is None or ex._grouped is not None or \
                ex._monitor_callback is not None:
            return False
        opt = self._optimizer
        if not callable(getattr(opt, "fused_update", None)) or \
                getattr(opt, "multi_precision", False):
            return False
        if any(ex.grad_req.get(n, "null") not in ("write", "null")
               for n in ex._arg_names):
            return False
        mesh = self._fit_mesh()
        if mesh is None or mesh.size() < 2:
            return False
        n = mesh.size()
        shapes = list(self._data_shapes) + list(self._label_shapes or [])
        if not shapes or not shapes[0].shape:
            return False
        batch = shapes[0].shape[0]
        if not batch or batch % n:
            return False  # batch must shard evenly over the mesh
        if any((not d.shape) or d.shape[0] != batch for d in shapes):
            return False
        return self._mesh_batch_outputs_ok(n, batch)

    # -- scanned K-step windows (fused_step.ScanTrainStep) -----------------
    def _scan_plan(self):
        from . import config as _config
        if self._scan_disabled:
            return None
        K = max(1, int(_config.get("MXNET_SCAN_STEPS")))
        M = max(1, int(_config.get("MXNET_SCAN_ACCUM")))
        if self._mesh_fused_eligible():
            # mesh path: even K=1 windows win (one donated dispatch
            # replaces 2 host round-trips per parameter).  The in-store
            # updater retires from the hot path NOW — optimizer state
            # lives in the module's Updater, which the mesh step
            # maintains, so state fetch and any later loop fallback
            # read one consistent store.
            if self._update_on_kvstore:
                # a checkpoint restore may have preloaded optimizer
                # state into the STORE's updater (set_optimizer_states
                # ran while update_on_kvstore was still true) — hand
                # those states to the module updater, or a resumed fit
                # would silently restart momentum/Adam moments at zero
                kv_updater = getattr(self._kvstore, "_updater", None)
                if kv_updater is not None:
                    for idx, st in kv_updater.states.items():
                        if isinstance(idx, int) and \
                                idx not in self._updater.states:
                            self._updater.states[idx] = st
                            self._updater.states_synced[idx] = True
                self._update_on_kvstore = False
            return (K, M, self._fit_mesh())
        if K * M <= 1:
            return None
        if not self._fused_eligible():
            if M > 1:
                self.logger.warning(
                    "MXNET_SCAN_ACCUM=%d requested but the setup is not "
                    "fused-step eligible; per-batch updates run WITHOUT "
                    "gradient accumulation", M)
                self._scan_disabled = True
            return None
        return (K, M, None)

    def _scan_batch_ok(self, batch):
        """Window-eligible: every data/label array matches its bound
        shape exactly (partial tails and bucket switches go per-batch)."""
        exec_ = self._exec
        for desc, arr in zip(self._data_shapes, batch.data):
            bound = exec_.arg_dict.get(desc.name)
            if bound is None or \
                    tuple(arr.shape) != tuple(bound.shape):
                return False
        if self._label_shapes and batch.label:
            for desc, arr in zip(self._label_shapes, batch.label):
                bound = exec_.arg_dict.get(desc.name)
                if bound is None or \
                        tuple(arr.shape) != tuple(bound.shape):
                    return False
        return True

    def _run_scan_window(self, sbatch, plan):
        """Dispatch one staged super-batch through the scanned step
        (mesh-fused when the plan carries a DeviceMesh); returns the
        flattened per-batch output buffers or False."""
        K, M, mesh = plan
        fs = self._scan
        if fs is None or fs.stale(self) or fs.scan_steps != K \
                or fs.accum != M or getattr(fs, "mesh", None) is not mesh:
            if mesh is not None:
                from .parallel import multihost as _mh
                from .parallel.fused import MeshFusedTrainStep
                if _mh.runtime() is not None and mesh.is_multiprocess:
                    # the coordinated multi-host flavor: per-window
                    # rendezvous, peer-watching bounded result waits,
                    # progress reporting (parallel/elastic.py)
                    from .parallel.elastic import MultiHostFusedTrainStep
                    fs = self._scan = MultiHostFusedTrainStep(
                        self, mesh, K, M)
                else:
                    fs = self._scan = MeshFusedTrainStep(self, mesh, K, M)
                self._mesh = mesh
                self.logger.info(
                    "mesh fused train step engaged: %s, K=%d M=%d — the "
                    "per-param kvstore push/pull loop is off the hot "
                    "path (kvstore remains for init/broadcast + "
                    "optimizer-state fetch)", mesh, K, M)
            else:
                from .fused_step import ScanTrainStep
                fs = self._scan = ScanTrainStep(self, K, M)
        outs = fs.run_window(sbatch)
        if outs is not False:
            self._forward_pad = 0
            self._fused_step_done = False
            if mesh is not None:
                # arg_dict/updater.states now hold mesh-replicated
                # arrays; any plain-executor use collapses them first
                self._mesh_arrays_live = True
        return outs

    def update(self):
        """Apply optimizer to gradients (parity: module.py update →
        model.py _update_params_on_kvstore / local updater).  After a
        fused forward_backward the weights are already updated and this
        is a no-op."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        if self._fused_step_done:
            self._fused_step_done = False
            return
        kv = getattr(self, "_kvstore", None)
        if kv is not None and self._update_on_kvstore:
            # optimizer runs IN the store (server-side for dist).  This
            # is the residual per-param sync path (mesh-ineligible
            # setups and real multi-worker clients): its wall time IS
            # gradient-communication time, so reattribute it from the
            # enclosing step_dispatch lane to comm_collective — the
            # breakdown then shows blocking-% on collectives directly.
            st = _telemetry.current_step_timer()
            t0 = time.perf_counter()
            _update_params_on_kvstore(
                [[self._exec.arg_dict[n]] for n in self._param_names],
                [[self._exec.grad_dict.get(n)] for n in self._param_names],
                kv, self._param_names)
            if st.active:
                dt = time.perf_counter() - t0  # graftlint: disable=raw-phase-timing -- lane REattribution: the span is already timed inside the step_dispatch lane; this moves its share to comm_collective
                st.add("comm_collective", dt)
                st.add("step_dispatch", -dt)
            self._zero_grads()
            return
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None or \
                    self._exec.grad_req.get(name, "null") == "null":
                continue  # fixed/ungradded params take no optimizer step
            weight = self._exec.arg_dict[name]
            self._updater(i, grad, weight)
        self._zero_grads()

    def _zero_grads(self):
        """Write-mode semantics for the next backward, WITHOUT the old
        one-dispatch-per-param ``grad[:] = 0.0`` loop: every grad NDArray
        swaps to a cached immutable zero buffer (jax arrays are
        copy-on-write, sharing is safe), so steady-state zeroing costs no
        device dispatch at all.  Params with no grad buffer or grad_req
        "null" are skipped."""
        import jax as _jax
        import jax.numpy as _jnp
        cache = self._zero_buf_cache
        for name in self._param_names:
            g = self._exec.grad_dict.get(name)
            if g is None or \
                    self._exec.grad_req.get(name, "null") == "null":
                continue
            dev = next(iter(g._data.devices()))
            key = (tuple(g.shape), str(g._data.dtype), dev)
            z = cache.get(key)
            if z is None:
                z = cache[key] = _jax.device_put(  # graftlint: disable=per-param-collective -- cold zero-buffer cache fill, once per (shape, dtype, device); steady state is a dict hit
                    _jnp.zeros(g.shape, g._data.dtype), dev)
            g._set_data(z)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        outs = self._exec.outputs
        pad = getattr(self, "_forward_pad", 0)
        if pad:
            # slice off the zero-padding rows added by the partial-batch
            # predict path (only outputs carrying the padded batch dim)
            bound = self._pad_bound
            batch_outs = getattr(self, "_pad_batch_outputs", None)
            if batch_outs is not None:
                # exact membership from shape inference at both batch
                # sizes (_infer_batch_outputs)
                outs = [o.slice_axis(0, 0, bound - pad)
                        if i in batch_outs else o
                        for i, o in enumerate(outs)]
            else:
                # inference couldn't decide: leading-dim heuristic
                outs = [o.slice_axis(0, 0, bound - pad)
                        if len(o.shape) >= 1 and o.shape[0] == bound else o
                        for o in outs]
        return outs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        """Feed (labels, outputs) to the metric.  The metric math runs on
        host numpy, so every call forces a device->host sync; with
        MXNET_METRIC_SYNC_INTERVAL=N the pairs are buffered (device
        arrays, no copy) and flushed every N batches — the device races
        ahead and the N transfers amortize into one stall.  Buffering
        requires label arrays that are not reused by the iterator
        (NDArrayIter and staged fit batches qualify; see docs)."""
        from . import config as _config
        label_map = {name: l for name, l in
                     zip([d.name for d in self._label_shapes], labels)}
        pred_map = dict(zip(self.output_names, self.get_outputs()))
        if _config.get("MXNET_METRIC_SYNC_INTERVAL") <= 1:
            st = _telemetry.current_step_timer()
            if st.active:
                # split the fit-loop lanes where the sync actually is:
                # device_block = waiting for the step's outputs to land,
                # metric_flush = the host-side metric math afterwards
                with st.lane("device_block"):
                    _block_on_maps(label_map, pred_map)
            with st.lane("metric_flush"):
                eval_metric.update_dict(label_map, pred_map)
            return
        self._pending_metric.append((eval_metric, label_map, pred_map, 1))
        if self._pending_metric_steps() >= \
                _config.get("MXNET_METRIC_SYNC_INTERVAL"):
            self.flush_metric_updates()

    def _pending_metric_steps(self):
        """Train steps represented in the metric buffer (a scanned window
        contributes K*M at once, so the flush interval rounds up to
        window boundaries)."""
        return sum(entry[3] for entry in self._pending_metric)

    def _window_update_metrics(self, eval_metric, sbatch, outs_flat):
        """Queue one whole window's metric inputs as STACKED arrays —
        zero per-step device ops here; the flush does ONE sync + one
        host transfer per tensor position and feeds the metric zero-copy
        numpy views per step.  Flushes immediately when metric syncing
        is per-batch (MXNET_METRIC_SYNC_INTERVAL <= 1), else once the
        buffered step count reaches the interval (rounded up to this
        window's boundary)."""
        from . import config as _config
        # a 1-step window (mesh path at K=M=1) strips its leading window
        # dim: the flush's single-step branch expects per-batch arrays
        unstack = sbatch.count == 1
        label_map = {}
        if self._label_shapes and sbatch.label:
            rows = getattr(self, "_mesh_local_rows", None)
            labels = sbatch.label
            if rows is not None:
                # multi-process mesh: outputs carry only this host's
                # addressable batch rows — pair them with the same
                # label rows (metrics are per-host over the local shard)
                labels = [l[:, rows[0]:rows[1]] for l in labels]
            label_map = {d.name: NDArray(l[0] if unstack else l,
                                         self._context)
                         for d, l in zip(self._label_shapes, labels)}
        pred_map = {name: NDArray(o[0] if unstack else o, self._context)
                    for name, o in zip(self.output_names, outs_flat)}
        self._pending_metric.append(
            (eval_metric, label_map, pred_map, sbatch.count))
        interval = _config.get("MXNET_METRIC_SYNC_INTERVAL")
        if interval <= 1 or self._pending_metric_steps() >= interval:
            self.flush_metric_updates()

    def flush_metric_updates(self):
        """Drain metric updates buffered under MXNET_METRIC_SYNC_INTERVAL
        (and whole scanned windows); the deferred device->host transfers
        all happen here, exactly once per buffered entry."""
        pending = self._pending_metric
        if not pending:
            return
        self._pending_metric = []
        st = _telemetry.current_step_timer()
        if st.active:
            with st.lane("device_block"):
                for _metric, label_map, pred_map, _n in pending:
                    _block_on_maps(label_map, pred_map)
        with st.lane("metric_flush"):
            for metric, label_map, pred_map, n in pending:
                if n == 1:
                    metric.update_dict(label_map, pred_map)
                    continue
                # stacked window entry (leading dim n): one host copy
                # per tensor, then zero-copy numpy views per step —
                # metrics consume numpy through _as_np unchanged
                lm = {k: v.asnumpy() for k, v in label_map.items()}  # graftlint: disable=host-sync-in-hot-path -- ONE batched transfer per stacked window tensor, this is the flush point
                pm = {k: v.asnumpy() for k, v in pred_map.items()}  # graftlint: disable=host-sync-in-hot-path -- ONE batched transfer per stacked window tensor, this is the flush point
                for j in range(n):
                    metric.update_dict(
                        {k: v[j] for k, v in lm.items()},
                        {k: v[j] for k, v in pm.items()})

    @property
    def output_names(self):
        return self.symbol.list_outputs()

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def output_shapes(self):
        if self._exec is not None and self._exec.outputs:
            return [(n, o.shape) for n, o in zip(self.output_names,
                                                 self._exec.outputs)]
        # before the first forward the executor has no output arrays yet
        # (reference modules report inferred shapes straight from bind) —
        # infer from the bound data/label shapes instead
        known = {d.name: d.shape for d in (self._data_shapes or [])}
        for l in (self._label_shapes or []):
            known[l.name] = l.shape
        _, outs, _ = self.symbol.infer_shape_partial(**known)
        return list(zip(self.output_names, outs or []))

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)


class BucketingModule(BaseModule):
    """Bucketing over variable-length inputs (parity:
    module/bucketing_module.py). One Module per bucket key; parameters are
    shared by name; each bucket compiles its own XLA program (one-compile-
    per-bucket is the TPU analogue of shared-memory executors per bucket)."""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = list(state_names or [])
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._initializer = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol

    def _gen_module(self, bucket_key):
        if bucket_key in self._buckets:
            return self._buckets[bucket_key]
        sym, data_names, label_names = self._sym_gen(bucket_key)
        mod = Module(sym, data_names, label_names, logger=self.logger,
                     context=self._context,
                     fixed_param_names=self._fixed_param_names,
                     state_names=self._state_names)
        self._buckets[bucket_key] = mod
        return mod

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        assert shared_module is None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                 force_rebind, None, grad_req)
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Switch to a bucket (parity: bucketing_module.py switch_bucket)."""
        assert self.binded
        if bucket_key == self._curr_bucket_key:
            return
        arg_params, aux_params = self._curr_module.get_params() \
            if self._curr_module.params_initialized else (None, None)
        mod = self._gen_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes, self.for_training,
                     self.inputs_need_grad)
        if arg_params is not None and not mod.params_initialized:
            mod.init_params(self._initializer, arg_params=arg_params,
                            aux_params=aux_params, allow_missing=False)
        elif arg_params is not None:
            mod.set_params(arg_params, aux_params)
        if self.optimizer_initialized and not mod.optimizer_initialized:
            mod._optimizer = self._curr_module._optimizer
            mod._updater = self._curr_module._updater
            # the kvstore wiring must follow the optimizer — otherwise a
            # bucket switch silently drops dist synchronization
            mod._kvstore = getattr(self._curr_module, "_kvstore", None)
            mod._update_on_kvstore = getattr(
                self._curr_module, "_update_on_kvstore", False)
            mod.optimizer_initialized = True
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if initializer is None:
            initializer = Uniform(0.01)
        self._initializer = initializer
        self._curr_module.init_params(initializer, arg_params, aux_params,
                                      allow_missing, force_init, allow_extra)
        self.params_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self._curr_module.set_params(arg_params, aux_params, allow_missing,
                                     force_init, allow_extra)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded
        bucket_key = getattr(data_batch, "bucket_key",
                             self._default_bucket_key)
        if bucket_key is None:
            bucket_key = self._default_bucket_key
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()
        # propagate updated params so other buckets see them on switch

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        for mod in self._buckets.values():
            mod.install_monitor(mon)


class SequentialModule(BaseModule):
    """Chain of modules (parity: module/sequential_module.py). Minimal
    implementation: forward feeds each module's outputs to the next."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        return self

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        my_data_shapes = data_shapes
        for i, module in enumerate(self._modules):
            meta = self._metas[i]
            my_label_shapes = label_shapes if meta.get(
                self.META_TAKE_LABELS) else None
            module.bind(my_data_shapes, my_label_shapes, for_training,
                        inputs_need_grad if i == 0 else True,
                        force_rebind, None, grad_req)
            my_data_shapes = [mx_io.DataDesc(name, shape) for name, shape
                              in module.output_shapes]
        self.binded = True

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        for module in self._modules:
            module.init_params(initializer, arg_params, aux_params,
                               allow_missing=True, force_init=force_init)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        for module in self._modules:
            module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        batch = data_batch
        for i, module in enumerate(self._modules):
            module.forward(batch, is_train)
            outs = module.get_outputs()
            batch = mx_io.DataBatch(data=outs, label=data_batch.label,
                                    pad=data_batch.pad)
        self._last_batch = batch

    def backward(self, out_grads=None):
        for i, module in reversed(list(enumerate(self._modules))):
            module.backward(out_grads)
            if i > 0:
                out_grads = module.get_input_grads()

    def update(self):
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for meta, module in zip(self._metas, self._modules):
            if meta.get(self.META_TAKE_LABELS):
                module.update_metric(eval_metric, labels, pre_sliced)


def _as_data_desc(x):
    if isinstance(x, mx_io.DataDesc):
        return x
    name, shape = x[0], x[1]
    return mx_io.DataDesc(name, tuple(shape))


class PythonModule(BaseModule):
    """A module whose computation is written directly in Python
    (parity: module/python_module.py PythonModule) — no symbol, no
    parameters by default. Subclasses implement forward/backward and
    ``_compute_output_shapes``; everything parameter/optimizer-shaped is
    a no-op so the module slots into SequentialModule pipelines and
    the fit() loop unchanged."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = tuple(data_names)
        self._label_names = tuple(label_names or ())
        self._output_names = tuple(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = [_as_data_desc(x) for x in data_shapes]
        self._label_shapes = ([_as_data_desc(x) for x in label_shapes]
                              if label_shapes else None)
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """[(name, shape)] of this module's outputs — subclass hook."""
        raise NotImplementedError()

    # -- parameters: none by default ---------------------------------------
    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        if self._label_shapes is not None:
            eval_metric.update(labels, self.get_outputs())


class PythonLossModule(PythonModule):
    """A Python-defined loss head (parity: module/python_module.py
    PythonLossModule): forward caches the incoming scores, backward
    produces the input gradient from ``grad_func(scores, labels)`` —
    the escape hatch for losses that are awkward as symbols, typically
    as the last stage of a SequentialModule."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__([name + "_" + d for d in data_names],
                         label_names,
                         [name + "_output"], logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None and not callable(grad_func):
            raise TypeError("grad_func must be callable")
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        # loss passes scores through: one output, shaped like the input
        return [(self._name + "_output", self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        assert out_grads is None, \
            "PythonLossModule is a loss head; it accepts no head grads"
        if self._grad_func is None:
            raise NotImplementedError(
                "PythonLossModule requires grad_func (the reference's "
                "fallback was an RTC CUDA kernel; provide the gradient "
                "of your loss w.r.t. the scores)")
        grad = self._grad_func(self._scores, self._labels)
        if not isinstance(grad, nd.NDArray):
            grad = nd.array(grad)
        self._scores_grad = grad

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]
