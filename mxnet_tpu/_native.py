"""ctypes loader for the native data-plane library (src/io_native.cc).

The reference implements its IO hot path in C++ (RecordIO parsing +
image batch assembly, src/io/iter_image_recordio_2.cc); this module loads
the TPU framework's native equivalent, building it on first use with
`make -C src` when a toolchain is present. Every caller has a pure-Python
fallback — absence of a compiler degrades performance, never capability.

Env: MXNET_NATIVE_IO=0 disables the native path entirely.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LOCK = threading.Lock()
_LIB = None
_TRIED = False

_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")
_LIB_PATH = os.path.join(_SRC_DIR, "build", "libmxnet_tpu_io.so")


def _build():
    try:
        subprocess.run(["make", "-C", _SRC_DIR],
                       check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False  # no toolchain / build failure: pure-Python paths


def _bind(lib):
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.mxio_scan_records.restype = ctypes.c_int64
    lib.mxio_scan_records.argtypes = [ctypes.c_char_p, i64p, i64p, i32p,
                                      ctypes.c_int64]
    lib.mxio_gather.restype = ctypes.c_int32
    lib.mxio_gather.argtypes = [ctypes.c_char_p, i64p, i64p,
                                ctypes.c_int64, u8p, i64p]
    lib.mxio_batch_transform.restype = None
    lib.mxio_batch_transform.argtypes = [
        u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, u8p, f32p, f32p, f32p]
    lib.mxio_batch_transform_f32.restype = None
    lib.mxio_batch_transform_f32.argtypes = [
        f32p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, u8p, f32p, f32p, f32p]
    lib.mxio_version.restype = ctypes.c_int32
    lib.mxio_version.argtypes = []
    lib.mxio_pipe_create.restype = ctypes.c_void_p
    lib.mxio_pipe_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_uint64, f32p, f32p, ctypes.c_int32, ctypes.c_int32]
    lib.mxio_pipe_next.restype = ctypes.c_int64
    lib.mxio_pipe_next.argtypes = [ctypes.c_void_p, f32p, f32p]
    lib.mxio_pipe_reset.restype = None
    lib.mxio_pipe_reset.argtypes = [ctypes.c_void_p]
    lib.mxio_pipe_num_batches.restype = ctypes.c_int64
    lib.mxio_pipe_num_batches.argtypes = [ctypes.c_void_p]
    lib.mxio_pipe_destroy.restype = None
    lib.mxio_pipe_destroy.argtypes = [ctypes.c_void_p]
    return lib


def get_lib():
    """The loaded native library, or None (fallback to Python)."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        from .config import get as _cfg
        if not _cfg("MXNET_NATIVE_IO"):
            return None
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            _LIB = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _LIB = None
        return _LIB


def available():
    return get_lib() is not None


def _fptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u8ptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i64ptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def scan_records(path, max_records=None):
    """Frame table of a .rec file: (offsets, lengths, cflags) int64/int32
    arrays of payload byte ranges. Raises on scan failure; returns None
    when the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    if max_records is None:
        # every frame is >= 8 bytes (header alone), so this bound is
        # exact — no silent truncation possible
        max_records = max(os.path.getsize(path) // 8, 1)
    offsets = np.empty(max_records, np.int64)
    lengths = np.empty(max_records, np.int64)
    cflags = np.empty(max_records, np.int32)
    n = lib.mxio_scan_records(
        path.encode(), _i64ptr(offsets), _i64ptr(lengths),
        cflags.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), max_records)
    if n < 0:
        raise IOError(f"native recordio scan failed for {path}")
    return offsets[:n].copy(), lengths[:n].copy(), cflags[:n].copy()


def gather(path, offsets, lengths):
    """Read byte ranges into one contiguous buffer; returns (buf,
    out_offsets) or None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    offsets = np.ascontiguousarray(offsets, np.int64)
    lengths = np.ascontiguousarray(lengths, np.int64)
    out_offsets = np.zeros(len(offsets), np.int64)
    np.cumsum(lengths[:-1], out=out_offsets[1:])
    buf = np.empty(int(lengths.sum()), np.uint8)
    rc = lib.mxio_gather(path.encode(), _i64ptr(offsets), _i64ptr(lengths),
                         len(offsets), _u8ptr(buf), _i64ptr(out_offsets))
    if rc != 0:
        raise IOError(f"native gather failed for {path}")
    return buf, out_offsets


def batch_transform(images, mirror=None, mean=None, std=None):
    """Fused cast+normalize+mirror+HWC->NCHW batch pack.

    images: [N,H,W,C] uint8 or float32 (contiguous). Returns [N,C,H,W]
    float32, or None when the native library is unavailable.
    """
    lib = get_lib()
    if lib is None:
        return None
    images = np.ascontiguousarray(images)
    n, h, w, c = images.shape
    if c > 16:
        return None  # mean/std channel buffer limit in the kernel
    out = np.empty((n, c, h, w), np.float32)
    mir = None
    if mirror is not None:
        mir = np.ascontiguousarray(mirror, np.uint8)
    # keep the contiguous copies alive across the call
    mean_c = np.ascontiguousarray(mean, np.float32).ravel() \
        if mean is not None else None
    std_c = np.ascontiguousarray(std, np.float32).ravel() \
        if std is not None else None
    meanp = _fptr(mean_c) if mean_c is not None else None
    stdp = _fptr(std_c) if std_c is not None else None
    if images.dtype == np.uint8:
        lib.mxio_batch_transform(
            _u8ptr(images), n, h, w, c,
            _u8ptr(mir) if mir is not None else None, meanp, stdp,
            _fptr(out))
    else:
        images = images.astype(np.float32, copy=False)
        lib.mxio_batch_transform_f32(
            _fptr(images), n, h, w, c,
            _u8ptr(mir) if mir is not None else None, meanp, stdp,
            _fptr(out))
    return out


class RecordPipe:
    """Native threaded record pipeline (reference: the
    iter_image_recordio_2.cc parser threads + ready-batch ring).  Reads
    RAW-pixel records (IRHeader + h*w*c uint8 body) and produces
    normalized NCHW float32 batches assembled by C++ worker threads that
    run ahead of the consumer.  Returns None from the constructor path
    (via create()) when the native lib is unavailable."""

    def __init__(self, handle, lib, batch, shape, label_width):
        self._h = handle
        self._lib = lib
        self.batch = batch
        self.shape = shape            # (c, h, w)
        self.label_width = label_width

    @classmethod
    def create(cls, path, batch_size, data_shape, label_width=1,
               shuffle=False, rand_mirror=False, seed=0, mean=None,
               std=None, prefetch=4, num_threads=2):
        lib = get_lib()
        if lib is None:
            return None
        c, h, w = data_shape
        mean_c = np.ascontiguousarray(mean, np.float32).ravel() \
            if mean is not None else None
        std_c = np.ascontiguousarray(std, np.float32).ravel() \
            if std is not None else None
        handle = lib.mxio_pipe_create(
            str(path).encode(), batch_size, h, w, c, label_width,
            1 if shuffle else 0, 1 if rand_mirror else 0, seed,
            _fptr(mean_c) if mean_c is not None else None,
            _fptr(std_c) if std_c is not None else None,
            prefetch, num_threads)
        if not handle:
            return None
        return cls(handle, lib, batch_size, data_shape, label_width)

    @property
    def num_batches(self):
        return int(self._lib.mxio_pipe_num_batches(self._h))

    def next_batch(self):
        """(data NCHW float32, label) or None at epoch end."""
        c, h, w = self.shape
        data = np.empty((self.batch, c, h, w), np.float32)
        label = np.empty((self.batch, self.label_width), np.float32)
        rc = int(self._lib.mxio_pipe_next(self._h, _fptr(data),
                                          _fptr(label)))
        if rc == -1:
            return None
        if rc < -1:
            raise RuntimeError(f"native record pipe IO error ({rc})")
        return data, label

    def reset(self):
        self._lib.mxio_pipe_reset(self._h)

    def __del__(self):
        try:
            if self._h:
                self._lib.mxio_pipe_destroy(self._h)
                self._h = None
        except Exception:  # graftlint: disable=swallowed-error -- __del__ during interpreter teardown must stay silent
            pass
