"""The registered kernels: the three seed-era Pallas one-offs promoted
into the registry contract.

Each spec pairs the Pallas implementation (parameterized by its tunable
config) with the pure-XLA reference that doubles as the numerics oracle
and the ``MXNET_KERNELS=reference`` executable.  The references are the
SAME functions the op layer runs with kernels off (plain_layer_norm /
plain_softmax_ce) — that identity is what makes reference-mode fits
bitwise-identical to kernels-off.
"""
from __future__ import annotations

import numpy as np

from ..ops import pallas_attention, pallas_norm, pallas_softmax_ce
from .registry import KernelSpec, register_kernel

_ROW_TILES = (256, 128, 64, 32, 16, 8, 4, 2, 1)


def _rows(shape):
    return int(np.prod(shape[:-1])) if len(shape) > 1 else int(shape[0])


# -- layernorm ----------------------------------------------------------------
def _ln_make(config):
    br = int(config["block_rows"])

    def impl(x, gamma, beta, eps=1e-5):
        return pallas_norm.fused_layer_norm(x, gamma, beta, eps=eps,
                                            block_rows=br)
    return impl


def _ln_reference(x, gamma, beta, eps=1e-5):
    return pallas_norm.plain_layer_norm(x, gamma, beta, eps=eps, axis=-1)


def _ln_space(shape, dtype):
    n = _rows(shape)
    cfgs = [{"block_rows": b} for b in _ROW_TILES if b <= n and n % b == 0]
    return cfgs or [{"block_rows": 1}]


def _ln_default(shape, dtype):
    return {"block_rows": pallas_norm._pick_block_rows(_rows(shape))}


def _ln_inputs(shape, dtype, rng):
    import jax.numpy as jnp
    d = int(shape[-1])
    x = jnp.asarray(rng.randn(*shape).astype(np.float32), dtype)
    gamma = jnp.asarray((1.0 + 0.1 * rng.randn(d)).astype(np.float32), dtype)
    beta = jnp.asarray((0.1 * rng.randn(d)).astype(np.float32), dtype)
    return (x, gamma, beta), {}


def _row_kernel_tol(dtype):
    import jax.numpy as jnp
    if jnp.dtype(dtype).itemsize < 4:
        # bf16/f16: the KERNEL keeps row stats in f32 while the
        # reference accumulates in-dtype, so most of the gap here is
        # reference rounding (~5% of gradient scale observed for bf16
        # LayerNorm bwd); still tight enough to catch O(1) math bugs
        return (2e-1, 2e-1)
    return (2e-5, 2e-5)


register_kernel(KernelSpec(
    name="layernorm",
    doc="fused trailing-axis LayerNorm (pallas_norm.py); config = row "
        "tile {block_rows}; fwd pallas, bwd analytic custom_vjp",
    reference=_ln_reference,
    make=_ln_make,
    config_space=_ln_space,
    default_config=_ln_default,
    example_inputs=_ln_inputs,
    grad_argnums=(0, 1, 2),
    tolerance=_row_kernel_tol,
))


# -- softmax cross-entropy ----------------------------------------------------
def _smce_make(config):
    br = int(config["block_rows"])

    def impl(logits, labels):
        return pallas_softmax_ce.softmax_ce_kernel(logits, labels,
                                                   block_rows=br)
    return impl


def _smce_space(shape, dtype):
    n = int(shape[0])
    cfgs = [{"block_rows": b} for b in _ROW_TILES if b <= n and n % b == 0]
    return cfgs or [{"block_rows": 1}]


def _smce_default(shape, dtype):
    return {"block_rows": pallas_softmax_ce._pick_block_rows(int(shape[0]))}


def _smce_inputs(shape, dtype, rng):
    import jax.numpy as jnp
    n, d = int(shape[0]), int(shape[1])
    logits = jnp.asarray(rng.randn(n, d).astype(np.float32), dtype)
    # include the -1 ignore/padding label so the gate proves the
    # zero-loss / zero-gradient convention, not just the happy path
    labels = rng.randint(0, d, size=n).astype(np.int32)
    if n > 1:
        labels[0] = -1
    return (logits, jnp.asarray(labels)), {}


def _smce_tol(dtype):
    import jax.numpy as jnp
    if jnp.dtype(dtype).itemsize < 4:
        return (2e-2, 2e-2)
    return (2e-5, 2e-5)


register_kernel(KernelSpec(
    name="softmax_ce",
    doc="fused per-row softmax + cross-entropy (pallas_softmax_ce.py); "
        "config = row tile {block_rows}; fwd pallas, bwd analytic "
        "(softmax - onehot) custom_vjp",
    reference=pallas_softmax_ce.plain_softmax_ce,
    make=_smce_make,
    config_space=_smce_space,
    default_config=_smce_default,
    example_inputs=_smce_inputs,
    grad_argnums=(0,),
    tolerance=_smce_tol,
))


# -- flash attention ----------------------------------------------------------
_ATTN_SPACE = ({"block_q": 128, "block_k": 128},
               {"block_q": 64, "block_k": 64},
               {"block_q": 64, "block_k": 128},
               {"block_q": 128, "block_k": 64},
               {"block_q": 256, "block_k": 128},
               {"block_q": 128, "block_k": 256})


def _attn_make(config):
    bq, bk = int(config["block_q"]), int(config["block_k"])

    def impl(q, k, v, causal=True, sm_scale=None):
        return pallas_attention.flash_attention(q, k, v, causal, sm_scale,
                                                bq, bk)
    return impl


def _attn_reference(q, k, v, causal=True, sm_scale=None):
    return pallas_attention.reference_attention(q, k, v, causal, sm_scale)


def _attn_space(shape, dtype):
    return [dict(c) for c in _ATTN_SPACE]


def _attn_default(shape, dtype):
    return {"block_q": 128, "block_k": 128}


def _attn_inputs(shape, dtype, rng):
    import jax.numpy as jnp
    q, k, v = (jnp.asarray(rng.randn(*shape).astype(np.float32), dtype)
               for _ in range(3))
    # causal is the serving configuration (GenerationEngine prefill) and
    # the harder masking case — gate what we ship
    return (q, k, v), {"causal": True}


def _attn_tol(dtype):
    import jax.numpy as jnp
    if jnp.dtype(dtype).itemsize < 4:
        return (4e-2, 4e-2)
    return (2e-4, 2e-4)   # online softmax reassociates the reduction


register_kernel(KernelSpec(
    name="attention",
    doc="blockwise (flash) causal attention (pallas_attention.py); "
        "config = MXU tiles {block_q, block_k}; fwd pallas online "
        "softmax, bwd rematerializing custom_vjp",
    reference=_attn_reference,
    make=_attn_make,
    config_space=_attn_space,
    default_config=_attn_default,
    example_inputs=_attn_inputs,
    grad_argnums=(0, 1, 2),
    tolerance=_attn_tol,
))
