"""Kernels smoke — the CI phase for the kernel layer.

Relay-proof (CPU, Pallas interpreter) proof obligations:

1. every registered kernel passes its interpreter-mode fwd+bwd
   correctness gate vs its jax reference, on every config of a tiny
   grid;
2. a tiny measured tune commits winners and persists them into the
   versioned namespace next to the PR 7 compile-cache ladders;
3. a SECOND process reloads those winners with ZERO re-tunes (asserted
   from the child's own counters);
4. a salt flip (fresh namespace) invalidates cleanly: the child falls
   back to heuristic defaults, still zero re-tunes, no crash;
5. trace budgets hold through the PR 7 ledger: one recorded tune trace
   per search, and re-resolving every kernel after tuning records
   nothing new.

Run: ``python -m mxnet_tpu.kernels.smoke`` (ci/run.sh kernels phase).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

# small shapes: the smoke proves mechanics, not device speed
SMOKE_SHAPES = {
    "layernorm": (64, 32),
    "softmax_ce": (64, 16),
    "attention": (2, 2, 32, 8),
}
SMOKE_GRIDS = {
    "layernorm": [{"block_rows": 64}, {"block_rows": 16}],
    "softmax_ce": [{"block_rows": 32}, {"block_rows": 8}],
    "attention": [{"block_q": 128, "block_k": 128},
                  {"block_q": 64, "block_k": 64}],
}


def _child():
    """Re-resolve every smoke shape and report sources + tune count."""
    import numpy as np

    from mxnet_tpu import kernels
    sources = {}
    for name, shape in SMOKE_SHAPES.items():
        kb = kernels.get(name, shape, np.float32)
        sources[name] = None if kb is None else kb.source
    print(json.dumps({"tunes": kernels.autotune.tunes_performed(),
                      "sources": sources}))
    return 0


def _spawn(env):
    out = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.kernels.smoke", "--child"],
        env=env, capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        raise SystemExit(f"kernels smoke child failed:\n{out.stdout}\n"
                         f"{out.stderr}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--child" in argv:
        return _child()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    cache_dir = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    if not cache_dir:
        cache_dir = tempfile.mkdtemp(prefix="mxnet-kernels-smoke-")
        os.environ["MXNET_COMPILE_CACHE_DIR"] = cache_dir
    os.environ["MXNET_KERNELS"] = "tuned"

    import numpy as np

    from mxnet_tpu import kernels
    from mxnet_tpu.compile.ledger import LEDGER
    from mxnet_tpu.kernels.registry import gate_report

    # 1. gates: the full tiny grid must be classifiable and pass
    print("== kernels smoke: interpreter-mode correctness gates ==")
    for name, shape in SMOKE_SHAPES.items():
        report = gate_report(name, shape, np.float32)
        bad = [key for key, ok in report.items() if not ok]
        assert not bad, f"kernel {name!r}: gate failed for {bad}"
        print(f"   {name}: {len(report)} configs gated, all pass")

    # 2. tune the tiny grid; winners must persist
    print("== kernels smoke: tiny-grid measured tune ==")
    before = LEDGER.trace_count("kernels/tune")
    for name, shape in SMOKE_SHAPES.items():
        cfg, source = kernels.tune(name, shape, np.float32,
                                   configs=SMOKE_GRIDS[name], repeats=1)
        assert source == "tuned", (name, source)
        print(f"   {name}: winner {cfg}")
    assert kernels.autotune.tunes_performed() == len(SMOKE_SHAPES)
    path = kernels.autotune.winners_path()
    assert os.path.exists(path), path

    # 5a. ledger budget: exactly one tune trace per search
    tuned_traces = LEDGER.trace_count("kernels/tune") - before
    assert tuned_traces == len(SMOKE_SHAPES), tuned_traces

    # 5b. re-resolving every kernel is ladder-cache work: zero new traces
    for name, shape in SMOKE_SHAPES.items():
        kb = kernels.get(name, shape, np.float32)
        assert kb is not None and kb.source == "tuned", (name, kb)
    assert LEDGER.trace_count("kernels/tune") - before == tuned_traces, \
        "re-resolution re-tuned"
    print("== kernels smoke: trace budget holds "
          f"({tuned_traces} tune traces, 0 on re-resolution) ==")

    # 3. second process: persisted winners reload, zero re-tunes
    env = dict(os.environ)
    child = _spawn(env)
    assert child["tunes"] == 0, child
    assert all(src == "persisted" for src in child["sources"].values()), \
        child
    print("== kernels smoke: second process reloaded persisted winners, "
          "0 re-tunes ==")

    # 4. salt flip: fresh namespace, clean fallback to defaults
    env_salt = dict(env, MXNET_COMPILE_CACHE_SALT="kernels-smoke-stale")
    child = _spawn(env_salt)
    assert child["tunes"] == 0, child
    assert all(src == "default" for src in child["sources"].values()), \
        child
    # the original namespace must survive the salted run untouched
    assert os.path.exists(path), "salt flip clobbered the live namespace"
    print("== kernels smoke: salt flip fell back to heuristic defaults, "
          "live namespace untouched ==")

    print("kernels smoke OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
