"""Per-shape measured autotuner with persisted winners.

TVM's lesson (PAPERS.md) applied at Pallas granularity: the best
tile/block config is a property of the concrete (shape, dtype), and a
measured search beats any fixed heuristic.  The tuner walks the spec's
config grid, gates each candidate (an incorrect config is never timed,
let alone selected), measures wall time with synchronized dispatches,
and commits the winner.

Winners persist under the SAME namespace policy as the PR 7 compile
cache: ``<cache_root()>/kernels/<version_key()>.json`` — any jax /
jaxlib / mxnet_tpu upgrade or ``MXNET_COMPILE_CACHE_SALT`` change
renames the namespace, so a stale stack never reloads foreign winners;
it just falls through the ladder.  Lookup order (the ladder):

  1. stats      — winners measured by THIS process,
  2. persisted  — winners reloaded from the namespace file,
  3. default    — the spec's heuristic config (always gated like any
                  other config before dispatch).

A corrupt/torn winners file is quarantined (renamed ``<path>.corrupt``)
with ONE warning and the ladder falls through — same doctrine as
planner.load_ladder.  The ``kernels/tune`` failpoint arms both the
mid-tune raise (partial measurements are discarded; nothing half-tuned
is ever committed) and byte corruption of the persisted file.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time

log = logging.getLogger("mxnet_tpu.kernels")

_lock = threading.Lock()
_winners = {}          # record key -> {"config", "ms", "source"}
_persisted = None      # lazily loaded file payload ({} when absent/corrupt)
_tunes = 0             # measured-search runs committed by THIS process
_warned_corrupt = set()


def record_key(name, shape, dtype):
    import jax.numpy as jnp
    dims = "x".join(str(int(s)) for s in shape)
    return f"{name}|{dims}|{jnp.dtype(dtype).name}"


def winners_path():
    from ..compile.cache import cache_root, version_key
    return os.path.join(cache_root(), "kernels", version_key() + ".json")


# -- persistence --------------------------------------------------------------
def _load():
    """The persisted winners map for the CURRENT namespace (cached)."""
    global _persisted
    with _lock:
        if _persisted is not None:
            return _persisted
    from ..compile.cache import version_key
    path = winners_path()
    loaded = {}
    try:
        with open(path) as f:
            payload = json.load(f)
        if payload.get("version") == version_key():
            for key, rec in payload.get("winners", {}).items():
                loaded[str(key)] = {"config": dict(rec["config"]),
                                    "ms": float(rec.get("ms", 0.0))}
        # a version-field mismatch (hand-copied file) is simply not ours:
        # fall through the ladder without quarantining a healthy file
    except FileNotFoundError:
        pass
    except Exception as e:  # noqa: BLE001 — a torn winners file must never crash a lookup
        with _lock:
            warned = path in _warned_corrupt
            _warned_corrupt.add(path)
        if not warned:
            log.warning(
                "corrupt persisted kernel tunings %r (%s: %s); "
                "quarantined — lookups fall back to heuristic defaults",
                path, type(e).__name__, e)
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            pass  # already moved/removed by a concurrent loader
    with _lock:
        if _persisted is None:
            _persisted = loaded
        return _persisted


def _save():
    """Write stats + persisted winners for this namespace atomically."""
    from ..chaos.failpoints import failpoint_bytes
    from ..compile.cache import version_key
    path = winners_path()
    merged = dict(_load())
    with _lock:
        for key, rec in _winners.items():
            merged[key] = {"config": rec["config"], "ms": rec["ms"]}
    payload = {"version": version_key(), "winners": merged}
    data = json.dumps(payload, indent=1, sort_keys=True).encode()
    data = failpoint_bytes("kernels/tune", data)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)
    return path


def stale_namespaces():
    """Winner files under ``<cache_root()>/kernels`` whose namespace no
    longer matches the running stack (prune candidates)."""
    from ..compile.cache import cache_root, version_key
    kdir = os.path.join(cache_root(), "kernels")
    if not os.path.isdir(kdir):
        return []
    current = version_key() + ".json"
    return sorted(f for f in os.listdir(kdir)
                  if f.endswith(".json") and f != current)


def prune_stale():
    """Delete stale winner namespaces; returns the file names removed.
    Same contract as compile.cache.prune_stale: explicit, never implicit."""
    from ..compile.cache import cache_root
    kdir = os.path.join(cache_root(), "kernels")
    removed = []
    for name in stale_namespaces():
        try:
            os.remove(os.path.join(kdir, name))
            removed.append(name)
        except OSError:
            pass  # lost a race with another pruner; the goal state holds
    return removed


# -- the ladder ---------------------------------------------------------------
def lookup(name, shape, dtype):
    """(config, source) through stats -> persisted -> heuristic default."""
    from .registry import get_spec
    key = record_key(name, shape, dtype)
    with _lock:
        rec = _winners.get(key)
    if rec is not None:
        return dict(rec["config"]), rec["source"]
    rec = _load().get(key)
    if rec is not None:
        with _lock:
            _winners[key] = {"config": dict(rec["config"]),
                             "ms": rec["ms"], "source": "persisted"}
        return dict(rec["config"]), "persisted"
    return dict(get_spec(name).default_config(shape, dtype)), "default"


def tunes_performed():
    with _lock:
        return _tunes


# -- measurement --------------------------------------------------------------
def _measure(fn, args, kwargs, repeats):
    """Best-of-``repeats`` wall ms for one synchronized dispatch.

    Runs on an isolated thread so a tune reached mid-trace still times
    concrete eager dispatches (see registry.run_host_isolated).
    """
    from .registry import run_host_isolated

    def _timed():
        import jax
        out = fn(*args, **kwargs)      # compile outside the timed region
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args, **kwargs))
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    return run_host_isolated(_timed)


def _tune_histogram():
    from ..telemetry import REGISTRY
    return REGISTRY.histogram(
        "mxnet_kernel_tune_seconds",
        "wall seconds per measured autotune search, by {kernel}")


def tune(name, shape, dtype, configs=None, repeats=None, persist=True):
    """Measured search over the config grid for one (shape, dtype).

    Gates every candidate first (an incorrect config is never eligible,
    tuned or not), measures the survivors, commits the winner to the
    stats rung and — with ``persist`` — the namespace file.  Returns
    ``(config, source)``.

    Never crashes the caller: any failure mid-search (including an
    armed ``kernels/tune`` raise) discards the partial measurements and
    falls back down the lookup ladder, with the fallback counted for
    the ``kernel_fallback`` alert.
    """
    global _tunes
    from .. import config as _config
    from ..compile.ledger import record_trace
    from .registry import config_key, gate, get_spec

    spec = get_spec(name)
    key = record_key(name, shape, dtype)
    if repeats is None:
        repeats = max(1, _config.get("MXNET_KERNELS_TUNE_REPEATS"))
    if configs is None:
        configs = list(spec.config_space(shape, dtype))
        budget = _config.get("MXNET_KERNELS_TUNE_BUDGET")
        if budget > 0 and len(configs) > budget:
            log.info("kernel %r tune grid capped at %d of %d configs "
                     "(MXNET_KERNELS_TUNE_BUDGET)", name, budget,
                     len(configs))
            configs = configs[:budget]

    t0 = time.perf_counter()
    try:
        from ..chaos.failpoints import failpoint
        rng_inputs = None
        measured = []   # partial results live HERE until the search completes
        for cfg in configs:
            failpoint("kernels/tune")
            if not gate(name, cfg, shape, dtype):
                continue
            if rng_inputs is None:
                import numpy as _np
                rng_inputs = spec.example_inputs(shape, dtype,
                                                 _np.random.RandomState(1))
            args, kwargs = rng_inputs
            ms = _measure(spec.make(dict(cfg)), args, kwargs, repeats)
            measured.append((ms, cfg))
            log.debug("kernel %r %s: %.3f ms", name, config_key(cfg), ms)
        if not measured:
            raise RuntimeError("no config survived the correctness gate")
    except Exception as e:  # noqa: BLE001 — a failed search degrades to the heuristic, never to a crash
        log.warning("kernel %r autotune aborted on shape=%s dtype=%s "
                    "(%s: %s); partial results discarded, falling back "
                    "down the lookup ladder", name, tuple(shape), dtype,
                    type(e).__name__, e)
        _fallback_counter_inc(name, "tune-aborted")
        return lookup(name, shape, dtype)

    ms, winner = min(measured, key=lambda t: t[0])
    with _lock:
        _winners[key] = {"config": dict(winner), "ms": ms,
                         "source": "tuned"}
        _tunes += 1
    record_trace("kernels/tune", reason=name)
    try:
        _tune_histogram().observe(time.perf_counter() - t0,
                                  labels={"kernel": name})
    except Exception:  # graftlint: disable=swallowed-error -- tuner accounting must never fail a tune that succeeded
        pass
    if persist:
        try:
            _save()
        except Exception as e:  # noqa: BLE001 — an unwritable cache degrades to per-process tuning
            log.warning("could not persist kernel tunings (%s: %s); "
                        "winners remain process-local",
                        type(e).__name__, e)
    return dict(winner), "tuned"


def _fallback_counter_inc(name, reason):
    try:
        from ..telemetry import REGISTRY
        REGISTRY.counter(
            "mxnet_kernel_fallback_total",
            "kernel lookups served by the reference implementation "
            "instead of a tuned/default Pallas config, by "
            "{kernel, reason}").inc(labels={"kernel": name,
                                            "reason": reason})
    except Exception:  # graftlint: disable=swallowed-error -- fallback accounting must never mask the fallback itself
        pass


def reset_for_tests():
    """Forget stats, the loaded file, and the tune count (test isolation)."""
    global _persisted, _tunes
    with _lock:
        _winners.clear()
        _persisted = None
        _tunes = 0
        _warned_corrupt.clear()
