"""mxnet_tpu.kernels — the single owner of hand-fused Pallas kernels.

``kernels.get(name, shape, dtype)`` is the ONE lookup the rest of the
tree uses.  It resolves the mode switch, walks the autotuner's lookup
ladder (stats -> persisted -> heuristic default), enforces the
correctness gate, and returns a callable :class:`BoundKernel` — or
``None`` when the subsystem is off and the caller should keep its
legacy path.

Mode switch (``MXNET_KERNELS``, default ``off``):

* ``off``       — subsystem disabled; ``get`` returns None.
* ``reference`` — serve the pure-XLA reference implementations (bitwise
                  identical to off for the op paths, by construction).
* ``tuned``     — serve the gated Pallas kernel at the best known
                  config; fall back to the reference (and count it) if
                  the config fails its gate.

``MXNET_KERNELS_OVERRIDES`` refines per kernel, e.g.
``layernorm=tuned,attention=off``.
"""
from __future__ import annotations

import logging
import threading

from ..base import MXNetError
from . import autotune  # noqa: F401  (re-export: kernels.autotune)
from .registry import (KernelSpec, config_key, gate, gate_report,  # noqa: F401
                       get_spec, list_kernels, register_kernel)
from . import library  # noqa: F401  (registers the built-in specs)

log = logging.getLogger("mxnet_tpu.kernels")

MODES = ("off", "reference", "tuned")

_lock = threading.Lock()
_BOUND = {}          # (name, shape, dtype, mode-env) -> BoundKernel | None
_SELECTED = {}       # (name, shape, dtype) -> selection record (collector)
_FALLBACK_WARNED = set()
_OVERRIDE_CACHE = {}


def _parse_overrides(raw):
    cached = _OVERRIDE_CACHE.get(raw)
    if cached is not None:
        return cached
    out = {}
    for part in raw.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise MXNetError(
                f"MXNET_KERNELS_OVERRIDES entry {part!r} is not "
                "'<kernel>=<mode>'")
        name, _, m = part.partition("=")
        name, m = name.strip(), m.strip().lower()
        if m not in MODES:
            raise MXNetError(
                f"MXNET_KERNELS_OVERRIDES: unknown mode {m!r} for "
                f"kernel {name!r}; expected one of {MODES}")
        out[name] = m
    _OVERRIDE_CACHE[raw] = out
    return out


def _mode_env():
    from .. import config as _config
    base = str(_config.get("MXNET_KERNELS")).strip().lower() or "off"
    if base not in MODES:
        raise MXNetError(
            f"MXNET_KERNELS={base!r}: expected one of {MODES}")
    return base, str(_config.get("MXNET_KERNELS_OVERRIDES")).strip()


def mode(name=None):
    """The effective mode — global, or for one kernel with overrides."""
    base, overrides = _mode_env()
    if name is None or not overrides:
        return base
    return _parse_overrides(overrides).get(name, base)


class BoundKernel:
    """A resolved kernel: implementation + the config/source that chose
    it.  Calling it is a plain passthrough — no lookups, no metrics, no
    host effects — so it is safe inside jit/scan/shard_map bodies."""

    __slots__ = ("name", "fn", "config", "source")

    def __init__(self, name, fn, config, source):
        self.name = name
        self.fn = fn
        self.config = config
        self.source = source

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __repr__(self):
        return (f"BoundKernel({self.name!r}, source={self.source!r}, "
                f"config={self.config!r})")


def _fallback(name, reason):
    autotune._fallback_counter_inc(name, reason)
    with _lock:
        warned = (name, reason) in _FALLBACK_WARNED
        _FALLBACK_WARNED.add((name, reason))
    if not warned:
        log.warning("kernel %r serving the reference implementation "
                    "(%s)", name, reason)


def get(name, shape, dtype):
    """Resolve ``name`` for a concrete (shape, dtype) under the current
    mode.  Returns a :class:`BoundKernel` or ``None`` (off).  Resolution
    is cached per exact key; resolve OUTSIDE traced bodies when you can
    (the serving engine resolves at model build), though trace-time
    resolution is also safe — it is trace-time Python, like any other
    static configuration.
    """
    import jax.numpy as jnp

    m = mode(name)
    if m == "off":
        return None
    shape = tuple(int(s) for s in shape)
    dt = jnp.dtype(dtype).name
    envkey = _mode_env()
    key = (name, shape, dt, m, envkey[1])
    with _lock:
        if key in _BOUND:
            return _BOUND[key]
    spec = get_spec(name)
    if m == "reference":
        bound = BoundKernel(name, spec.reference, None, "reference")
    else:
        try:
            cfg, source = autotune.lookup(name, shape, dtype)
            if gate(name, cfg, shape, dtype):
                bound = BoundKernel(name, spec.make(dict(cfg)), cfg, source)
            else:
                _fallback(name, "gate-failed")
                bound = BoundKernel(name, spec.reference, None,
                                    "fallback-reference")
        except Exception as e:  # noqa: BLE001 — a broken lookup serves the reference, never a crash
            _fallback(name, f"lookup-error:{type(e).__name__}")
            bound = BoundKernel(name, spec.reference, None,
                                "fallback-reference")
    with _lock:
        _BOUND[key] = bound
        _SELECTED[(name, shape, dt)] = {
            "kernel": name, "mode": m, "source": bound.source,
            "config": bound.config, "shape": shape, "dtype": dt}
    return bound


def tune(name, shape, dtype, **kwargs):
    """Explicit measured tune (see autotune.tune); invalidates the bound
    cache so the next ``get`` serves the fresh winner."""
    result = autotune.tune(name, shape, dtype, **kwargs)
    with _lock:
        _BOUND.clear()
    return result


def reset_for_tests():
    """Full subsystem reset: bound cache, selections, gate cache, tuner."""
    from .registry import reset_gate_cache
    with _lock:
        _BOUND.clear()
        _SELECTED.clear()
        _FALLBACK_WARNED.clear()
    reset_gate_cache()
    autotune.reset_for_tests()


# -- telemetry collector ------------------------------------------------------
def _collector_snapshot():
    base, overrides = _mode_env()
    with _lock:
        selected = {f"{k[0]}|{'x'.join(map(str, k[1]))}|{k[2]}": dict(v)
                    for k, v in _SELECTED.items()}
    return {"mode": base, "overrides": overrides,
            "registered": list_kernels(),
            "tunes_performed": autotune.tunes_performed(),
            "selected": selected}


def _collector_samples():
    with _lock:
        records = list(_SELECTED.values())
    out = []
    for rec in records:
        out.append((
            "mxnet_kernel_selected_config", "gauge",
            "active kernel selection per (kernel, shape, dtype); value 1, "
            "identity in {kernel, shape, dtype, source, config}",
            {"kernel": rec["kernel"], "shape":
             "x".join(map(str, rec["shape"])), "dtype": rec["dtype"],
             "source": rec["source"],
             "config": config_key(rec["config"])},
            1.0))
    return out


def _register_collector():
    try:
        from ..telemetry import REGISTRY
        REGISTRY.register_collector("kernels", _collector_snapshot,
                                    _collector_samples)
    except Exception as e:  # noqa: BLE001 — observability must not break the kernels import
        log.debug("kernels collector not registered: %s", e)


_register_collector()
