"""Kernel registry + correctness gates — the kernels-layer contract.

Every hand-fused kernel in the tree is declared here as a
:class:`KernelSpec`: its pure-XLA reference implementation, its tunable
config space (tile/block choices), a heuristic default config, and the
tolerance its outputs must meet.  The registry enforces ONE invariant
before any tuned config becomes eligible: the interpreter-mode
correctness gate — forward AND backward (through the kernel's
custom_vjp) must match the reference within the spec's stated tolerance
on this exact (config, shape, dtype).  A config that has not passed its
gate is never dispatched; a config that fails falls back to the
reference implementation and increments the fallback counter the
``kernel_fallback`` alert watches.

The gate runs on CPU (Pallas interpreter) by design: with the TPU relay
down, interpreter-mode-vs-reference is the relay-proof correctness
evidence, and the identical kernel bodies run under Mosaic once a
device shows up (ROADMAP "relay-proof CPU gate" doctrine).
"""
from __future__ import annotations

import json
import logging
import threading

import numpy as np

from ..base import MXNetError

log = logging.getLogger("mxnet_tpu.kernels")

_lock = threading.Lock()
_SPECS = {}
_GATE_CACHE = {}   # (name, cfg_key, shape, dtype) -> bool
_GATE_WARNED = set()


class KernelSpec:
    """Declaration of one fused kernel.

    * ``reference(*args, **kwargs)`` — pure jax/XLA implementation; the
      numerics oracle AND the fallback executable.
    * ``make(config)`` — build the Pallas implementation for one config
      dict; same call signature as ``reference``.
    * ``config_space(shape, dtype)`` — candidate config dicts for a
      concrete shape/dtype (the autotuner's search grid).
    * ``default_config(shape, dtype)`` — the heuristic config used when
      nothing tuned/persisted exists (last rung of the lookup ladder).
    * ``example_inputs(shape, dtype, rng)`` — ``(args, kwargs)`` used by
      the gate and the tuner's measurements.
    * ``grad_argnums`` — which positional args the gate differentiates.
    * ``tolerance(dtype)`` — ``(rtol, atol)`` for fwd and bwd compares.
    """

    __slots__ = ("name", "doc", "reference", "make", "config_space",
                 "default_config", "example_inputs", "grad_argnums",
                 "tolerance")

    def __init__(self, name, doc, reference, make, config_space,
                 default_config, example_inputs, grad_argnums,
                 tolerance):
        self.name = str(name)
        self.doc = doc
        self.reference = reference
        self.make = make
        self.config_space = config_space
        self.default_config = default_config
        self.example_inputs = example_inputs
        self.grad_argnums = tuple(grad_argnums)
        self.tolerance = tolerance


def register_kernel(spec):
    if not isinstance(spec, KernelSpec):
        raise MXNetError("register_kernel expects a KernelSpec")
    with _lock:
        _SPECS[spec.name] = spec
    return spec


def get_spec(name):
    spec = _SPECS.get(name)
    if spec is None:
        raise MXNetError(
            f"unknown kernel {name!r}; registered: {sorted(_SPECS)}")
    return spec


def list_kernels():
    with _lock:
        return sorted(_SPECS)


def config_key(config):
    """Canonical string for a config dict (persistence + cache keys)."""
    return json.dumps(config or {}, sort_keys=True, separators=(",", ":"))


def _gate_counter():
    from ..telemetry import REGISTRY
    return REGISTRY.counter(
        "mxnet_kernel_gate_total",
        "kernel correctness-gate outcomes by {kernel, result}")


def _run(fn, args, kwargs, grad_argnums):
    """(forward output, grads at grad_argnums) — through whatever vjp
    the implementation defines (custom_vjp for the Pallas kernels,
    plain autodiff for references)."""
    import jax
    import jax.numpy as jnp

    out = fn(*args, **kwargs)

    def loss(*diff):
        full = list(args)
        for i, v in zip(grad_argnums, diff):
            full[i] = v
        o = fn(*full, **kwargs)
        return jnp.sum(jnp.square(o.astype(jnp.float32)))

    grads = jax.grad(loss, argnums=tuple(range(len(grad_argnums))))(
        *[args[i] for i in grad_argnums])
    return out, grads


def _close(a, b, rtol, atol):
    return np.allclose(np.asarray(a, dtype=np.float32),
                       np.asarray(b, dtype=np.float32),
                       rtol=rtol, atol=atol)


def gate(name, config, shape, dtype):
    """Interpreter-mode fwd+bwd correctness gate vs the reference.

    True iff the kernel built from ``config`` matches the spec's
    reference within tolerance on ``(shape, dtype)`` — cached per exact
    key, so the real cost is paid once per process.  A False here means
    the caller MUST NOT dispatch this config (kernels.get serves the
    reference instead and counts the fallback).
    """
    import jax.numpy as jnp

    spec = get_spec(name)
    key = (name, config_key(config), tuple(int(s) for s in shape),
           jnp.dtype(dtype).name)
    with _lock:
        hit = _GATE_CACHE.get(key)
    if hit is not None:
        return hit
    ok, detail = _gate_once(spec, config, shape, dtype)
    with _lock:
        _GATE_CACHE[key] = ok
    try:
        _gate_counter().inc(labels={"kernel": name,
                                    "result": "pass" if ok else "fail"})
    except Exception:  # graftlint: disable=swallowed-error -- gate accounting must never change the gate's answer
        pass
    if not ok:
        with _lock:
            warned = key in _GATE_WARNED
            _GATE_WARNED.add(key)
        if not warned:
            log.warning(
                "kernel %r config %s FAILED its correctness gate on "
                "shape=%s dtype=%s (%s); this config is ineligible — "
                "callers fall back to the reference implementation",
                name, config_key(config), tuple(shape),
                jnp.dtype(dtype).name, detail)
    return ok


def run_host_isolated(fn):
    """Run ``fn()`` on a fresh thread and return its result.

    JAX trace state is thread-local: the gate (and the tuner's
    measurements) may be reached from inside someone else's trace — an
    op resolving its kernel while a scan/jit body traces.  A worker
    thread gives these concrete example runs a clean eager context that
    no ambient trace can capture into its jaxpr.
    """
    box = {}

    def _work():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller's thread below
            box["error"] = e

    t = threading.Thread(target=_work, name="mxnet-kernels-eval")
    t.start()
    t.join()
    if "error" in box:
        raise box["error"]
    return box["value"]


def _gate_eval(spec, config, shape, dtype):
    rng = np.random.RandomState(0)
    args, kwargs = spec.example_inputs(shape, dtype, rng)
    rtol, atol = spec.tolerance(dtype)
    impl = spec.make(dict(config or {}))
    out_k, grads_k = _run(impl, args, kwargs, spec.grad_argnums)
    out_r, grads_r = _run(spec.reference, args, kwargs,
                          spec.grad_argnums)
    if not _close(out_k, out_r, rtol, atol):
        return False, "forward mismatch"
    for i, (gk, gr) in enumerate(zip(grads_k, grads_r)):
        if not _close(gk, gr, rtol, atol):
            return False, f"backward mismatch (arg {spec.grad_argnums[i]})"
    return True, ""


def _gate_once(spec, config, shape, dtype):
    try:
        return run_host_isolated(
            lambda: _gate_eval(spec, config, shape, dtype))
    except Exception as e:  # noqa: BLE001 — a crashing config is an ineligible config, not a crashed caller
        return False, f"{type(e).__name__}: {e}"


def gate_report(name, shape, dtype):
    """Gate every config in the spec's space; {config_key: bool}.  The
    smoke phase uses this to prove the whole grid is classifiable."""
    spec = get_spec(name)
    return {config_key(c): gate(name, c, shape, dtype)
            for c in spec.config_space(shape, dtype)}


def reset_gate_cache():
    with _lock:
        _GATE_CACHE.clear()
        _GATE_WARNED.clear()
