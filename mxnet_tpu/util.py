"""General utilities (parity: python/mxnet/util.py — makedirs, device
discovery helpers, and the numpy-shape-semantics scope).

The np_shape machinery deserves a note: the reference uses it to gate
zero-size/zero-dim tensor support in its C++ shape inference (legacy
MXNet treated 0 as "unknown"). This framework sits on jax, where
`()`-shaped and 0-size arrays are first-class — so numpy semantics are
always available; the scope still exists (thread-local flag, context
manager, decorator) so reference code that toggles it runs unchanged,
and `is_np_shape()` faithfully reports what the caller set.
"""
from __future__ import annotations

import functools
import os
import threading

_state = threading.local()


def makedirs(d):
    """mkdir -p (parity: util.py makedirs)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def get_gpu_count():
    """Accelerator count (parity: util.py get_gpu_count)."""
    from .context import num_tpus, num_gpus
    return num_tpus() or num_gpus()


def get_gpu_memory(gpu_dev_id=0):
    """(free, total) bytes for a device (parity: util.py
    get_gpu_memory)."""
    from .context import device_memory_info, tpu
    info = device_memory_info(tpu(gpu_dev_id))
    total = int(info.get("bytes_limit", 0))
    used = int(info.get("bytes_in_use", 0))
    return total - used, total


def set_np_shape(active):
    """Toggle numpy shape semantics (parity: util.py set_np_shape).
    Returns the previous state."""
    prev = is_np_shape()
    _state.np_shape = bool(active)
    return prev


def is_np_shape():
    return getattr(_state, "np_shape", False)


class _NumpyShapeScope:
    def __init__(self, is_np_shape_):
        self._active = is_np_shape_
        self._prev = None

    def __enter__(self):
        self._prev = set_np_shape(self._active)
        return self

    def __exit__(self, *exc):
        set_np_shape(self._prev)


def np_shape(active=True):
    """Context manager enabling numpy shape semantics (parity:
    util.py np_shape)."""
    return _NumpyShapeScope(active)


def use_np_shape(func):
    """Decorator running ``func`` under np_shape(True) (parity:
    util.py use_np_shape; works on functions and classes' methods)."""
    if isinstance(func, type):
        for name, attr in list(vars(func).items()):
            if callable(attr) and not name.startswith("__"):
                setattr(func, name, use_np_shape(attr))
        return func

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with np_shape(True):
            return func(*args, **kwargs)

    return wrapper
