"""mxnet_tpu.io_pipeline — sharded streaming data plane (ISSUE 19).

PRs 6/9/11 drove the device side to 1/K dispatches per step; the input
feed stayed a serial prefix on the train thread — it read, decoded,
stacked and staged every super-batch while the accelerator idled.  This
module pipelines that last serial stage:

* a **shard source** splits the dataset into independently readable
  shards (in-memory arrays or raw-pixel RecordIO byte ranges);
* a **seeded per-epoch shard order** (``MXNET_DATA_SHARD_SEED``) fixes
  the batch sequence BEFORE any worker runs — the same order is
  produced for any worker count, which is the load-bearing invariant
  behind the bitwise fit-parity guarantee (docs/data.md);
* a pool of **reader workers** (``MXNET_DATA_WORKERS``) claims shard
  positions — each worker statically prefers its own slice of the
  order (position ``p`` with ``p % workers == wid``) so a healthy pool
  never contends, and steals the earliest eligible position otherwise;
* each position owns a **bounded output queue**
  (``MXNET_DATA_QUEUE_DEPTH`` batches) and only positions inside a
  bounded **in-flight window** are claimable, so total buffered
  batches — and host RSS under the PR-13 sampler — stay capped no
  matter how far the readers could run ahead;
* the **assembler** (the consumer side of :class:`DataPipeline`)
  drains queues in global order, so the delivered batch sequence is
  identical to a serial read of the same order;
* a dead or poisoned reader is **rebalanced**: its in-progress shard
  is requeued (resuming at the first undelivered batch — every sample
  delivered exactly once) and its remaining slice is absorbed by the
  survivors' steal path; a typed :class:`DataReaderError` is raised
  only when ALL readers are gone — a starved consumer never stalls;
* :class:`WindowFeed` applies the PR-10 stage/dispatch thread-pair
  idiom to training input: a staging thread collects K*M batches and
  runs ``io.stage_super_batch`` OFF the train thread, double-buffered
  so window N+1 stages while window N executes.

Chaos site ``io/reader/read`` fires in the reader loop per batch
(delay = slow reader, raise = dead reader).  Telemetry:
``mxnet_data_wait_seconds`` / ``mxnet_data_queue_depth`` /
``mxnet_data_batches_total`` / ``mxnet_data_rebalance_total``.
"""
from __future__ import annotations

import logging
import queue as _queue
import struct
import threading
import time
import weakref

import numpy as np

from . import io as mx_io
from . import ndarray as nd
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter


class DataReaderError(MXNetError):
    """Typed: every reader worker of a :class:`DataPipeline` died.

    Raised from the consumer side (``next()``) once the buffered
    batches are drained — a job-level failure the caller can retry or
    surface, never a silent stall."""


#: live pipelines, for the ``mxnet_data_queue_depth`` alert probe
#: (weak: pipelines come and go with fits)
_ACTIVE = weakref.WeakSet()

#: a pipeline that made no put/get progress for this long stops
#: answering the queue-depth probe — an absence rule on
#: ``mxnet_data_queue_depth`` then sees the family go silent
#: (docs/observability.md)
PROBE_FRESH_S = 15.0

_END_OF_SHARD = object()


class _Shutdown(Exception):
    """Internal: reader told to exit (reset/close); not an error."""


def queue_depth_samples():
    """``(labels, value)`` rows for the alert engine's
    ``mxnet_data_queue_depth`` probe: one row per live pipeline role
    that made progress within :data:`PROBE_FRESH_S`.  A wedged
    assembler stops refreshing its row, so an ``absence`` rule fires
    while the train/fit watchdog walks up to its page."""
    now = time.monotonic()
    out = []
    for pipe in list(_ACTIVE):
        if now - pipe._last_progress <= PROBE_FRESH_S:
            out.append(({"role": "shards"}, float(pipe.buffered())))
    return out


# -- shard sources ------------------------------------------------------------
class ShardSource:
    """A dataset split into independently readable shards.

    Subclasses fix ``num_shards`` at construction and implement
    :meth:`read_shard` as a generator of :class:`io.DataBatch`; the
    ``start`` argument skips already-delivered batches when a shard is
    requeued after a reader death (the exactly-once contract)."""

    batch_size = 0

    @property
    def provide_data(self):
        raise NotImplementedError()

    @property
    def provide_label(self):
        raise NotImplementedError()

    def num_shards(self):
        raise NotImplementedError()

    def read_shard(self, shard, start=0):
        raise NotImplementedError()


class NDArraySource(ShardSource):
    """In-memory arrays as a shard source (the NDArrayIter twin).

    Batches are ``batch_size`` consecutive rows; a shard is
    ``batches_per_shard`` consecutive batches; trailing rows that do
    not fill a batch are discarded (``last_batch_handle='discard'``
    semantics — shards must be uniform for the window path anyway)."""

    def __init__(self, data, label=None, batch_size=1, batches_per_shard=1,
                 data_name="data", label_name="softmax_label"):
        if batch_size < 1 or batches_per_shard < 1:
            raise MXNetError("NDArraySource: batch_size and "
                             "batches_per_shard must be >= 1")
        self.data = mx_io._init_data(data, allow_empty=False,
                                     default_name=data_name)
        self.label = mx_io._init_data(label, allow_empty=True,
                                      default_name=label_name)
        self.batch_size = batch_size
        self.batches_per_shard = batches_per_shard
        self.num_batches = self.data[0][1].shape[0] // batch_size
        self._n_shards = -(-self.num_batches // batches_per_shard) \
            if self.num_batches else 0

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def num_shards(self):
        return self._n_shards

    def read_shard(self, shard, start=0):
        first = shard * self.batches_per_shard
        last = min(first + self.batches_per_shard, self.num_batches)
        for b in range(first + start, last):
            r0 = b * self.batch_size
            r1 = r0 + self.batch_size
            yield DataBatch(
                data=[nd.array(v[r0:r1]) for _, v in self.data],
                label=[nd.array(v[r0:r1]) for _, v in self.label],
                pad=0, index=np.arange(r0, r1))


class RecordFileSource(ShardSource):
    """RAW-pixel RecordIO file as a shard source.

    Scans the dmlc recordio framing once (the offset-table twin of
    ``io.RawRecordIter._py_scan_offsets``), then serves shards as
    contiguous record ranges — each reader seeks into its own range,
    so shards decode independently and in parallel.  Records must hold
    IRHeader + h*w*c uint8 pixels (``recordio.pack``)."""

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 batches_per_shard=1, mean=None, std=None):
        self._path = str(path_imgrec)
        self.data_shape = tuple(data_shape)
        self.batch_size = batch_size
        self.label_width = label_width
        self.batches_per_shard = batches_per_shard
        self._mean = np.asarray(mean, np.float32) if mean is not None \
            else None
        self._std = np.asarray(std, np.float32) if std is not None else None
        self._offsets = self._scan_offsets()
        self.num_batches = len(self._offsets) // batch_size
        self._n_shards = -(-self.num_batches // batches_per_shard) \
            if self.num_batches else 0

    def _scan_offsets(self):
        out = []
        with open(self._path, "rb") as f:
            while True:
                head = f.read(8)
                if len(head) < 8:
                    break
                magic, lrec = struct.unpack("<II", head)
                if magic != 0xced7230a:
                    raise MXNetError(f"bad recordio magic in {self._path}")
                cflag, ln = lrec >> 29, lrec & ((1 << 29) - 1)
                if cflag == 0:
                    out.append((f.tell(), ln))
                f.seek(ln + ((4 - ln % 4) % 4), 1)
        return out

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label",
                         (self.batch_size, self.label_width))]

    def num_shards(self):
        return self._n_shards

    def read_shard(self, shard, start=0):
        from . import recordio
        c, h, w = self.data_shape
        n = self.batch_size
        first = shard * self.batches_per_shard
        last = min(first + self.batches_per_shard, self.num_batches)
        with open(self._path, "rb") as f:
            for b in range(first + start, last):
                data = np.empty((n, c, h, w), np.float32)
                label = np.zeros((n, self.label_width), np.float32)
                for i in range(n):
                    off, ln = self._offsets[b * n + i]
                    f.seek(off)
                    header, body = recordio.unpack(f.read(ln))
                    lbl = np.asarray(header.label).ravel()
                    label[i, :min(len(lbl), self.label_width)] = \
                        lbl[:self.label_width]
                    x = np.frombuffer(body, np.uint8).reshape(h, w, c) \
                        .astype(np.float32)
                    if self._mean is not None:
                        x = x - self._mean
                    if self._std is not None:
                        x = x / self._std
                    data[i] = x.transpose(2, 0, 1)
                yield DataBatch(data=[nd.array(data)],
                                label=[nd.array(label)], pad=0,
                                index=np.arange(b * n, b * n + n))


# -- the pipeline -------------------------------------------------------------
class _ShardJob:
    """One position of the epoch shard order: its bounded output queue
    plus the delivered-batch watermark that makes requeue-after-death
    exactly-once (the new owner resumes at ``delivered``)."""

    __slots__ = ("shard", "queue", "delivered", "state", "owner",
                 "inline", "idle_polls")

    def __init__(self, shard, depth):
        self.shard = shard
        # +1: the end-of-shard sentinel rides the same queue but must
        # not eat a batch slot (``depth`` means depth BATCHES buffered)
        self.queue = _queue.Queue(maxsize=depth + 1)
        self.delivered = 0     # batches put into the queue so far
        self.state = "pending"  # pending -> active -> produced -> consumed
        self.owner = None
        self.inline = None     # assembler-rescue generator
        self.idle_polls = 0


def epoch_shard_order(num_shards, seed, epoch, num_parts=1, part_index=0):
    """The seeded per-epoch shard order — the determinism contract.

    A function of ``(num_shards, seed, epoch)`` ONLY: worker count,
    queue depth and scheduling never enter, so every configuration
    replays the same batch sequence.  Multi-process meshes slice the
    one global permutation per rank (``order[part_index::num_parts]``,
    the LibSVMIter num_parts contract) so ranks read disjoint shards
    of the same epoch."""
    rng = np.random.RandomState((int(seed) + int(epoch)) & 0x7fffffff)
    order = rng.permutation(num_shards)
    if num_parts > 1:
        order = order[part_index::num_parts]
    return [int(s) for s in order]


class DataPipeline(DataIter):
    """Multi-worker streaming iterator over a :class:`ShardSource`.

    ``workers=0`` reads the same seeded shard order serially on the
    calling thread — the bitwise-identical baseline (and the bench
    phase's serial-loop comparator).  ``workers>0`` runs the reader
    pool described in the module docstring; the delivered sequence is
    identical in both modes."""

    def __init__(self, source, workers=None, queue_depth=None, seed=None,
                 num_parts=1, part_index=0, max_inflight=None):
        from . import config as _config
        super().__init__(source.batch_size)
        self._source = source
        self._workers = int(_config.get("MXNET_DATA_WORKERS")
                            if workers is None else workers)
        self._depth = max(1, int(_config.get("MXNET_DATA_QUEUE_DEPTH")
                                 if queue_depth is None else queue_depth))
        self._seed = int(_config.get("MXNET_DATA_SHARD_SEED")
                         if seed is None else seed)
        self._num_parts = int(num_parts)
        self._part_index = int(part_index)
        self._max_inflight = int(max_inflight) if max_inflight else \
            max(2 * self._workers, self._workers + 2)
        self._epoch = 0
        self._cond = threading.Condition()
        self._threads = []
        self._stop = threading.Event()
        self._jobs = []
        self._buffered = 0          # batches in queues (backpressure gauge)
        self._last_progress = time.monotonic()
        self._fatal = None          # the last reader's fatal exception
        self._live = 0
        self._pos = 0               # assembler cursor into the order
        self._base = 0              # first unconsumed position
        self._serial = None         # workers==0 generator
        self._started = False
        _ACTIVE.add(self)
        self._begin_epoch()

    # -- epoch lifecycle -----------------------------------------------------
    @property
    def provide_data(self):
        return self._source.provide_data

    @property
    def provide_label(self):
        return self._source.provide_label

    @property
    def workers(self):
        return self._workers

    def epoch_order(self):
        """This epoch's shard order for THIS rank (testing hook)."""
        return epoch_shard_order(self._source.num_shards(), self._seed,
                                 self._epoch, self._num_parts,
                                 self._part_index)

    def _begin_epoch(self):
        order = self.epoch_order()
        with self._cond:
            self._jobs = [_ShardJob(s, self._depth) for s in order]
            self._pos = 0
            self._base = 0
            self._buffered = 0
            self._fatal = None
            self._serial = None
            self._started = False

    def _start(self):
        with self._cond:
            if self._started:
                return
            self._started = True
            jobs = list(self._jobs)
            if self._workers <= 0:
                def serial():
                    from . import telemetry as _telemetry
                    for job in jobs:
                        for b in self._source.read_shard(job.shard):
                            _telemetry.record_data_batches(1)
                            yield b
                self._serial = serial()
                return
            self._stop = threading.Event()
            self._live = self._workers
            stop = self._stop
        threads = []
        for wid in range(self._workers):
            t = threading.Thread(
                target=self._reader, args=(wid, stop),
                name=f"mx-data-reader-{wid}", daemon=True)
            t.start()
            threads.append(t)
        with self._cond:
            self._threads = threads

    def _shutdown(self):
        """Stop this epoch's readers: signal, drain (a put-blocked
        reader needs queue space to see the stop), then join."""
        with self._cond:
            self._stop.set()
            threads = list(self._threads)
            jobs = list(self._jobs)
            self._cond.notify_all()
        for t in threads:
            while t.is_alive():
                for job in jobs:
                    try:
                        while True:
                            job.queue.get_nowait()
                    except _queue.Empty:
                        pass
                t.join(timeout=0.2)
        with self._cond:
            self._threads = []
            self._serial = None

    def reset(self):
        with self._cond:
            started = self._started
        if started:
            self._shutdown()
        self._epoch += 1
        self._begin_epoch()

    def close(self):
        """Tear the pool down without starting another epoch."""
        with self._cond:
            started = self._started
            self._started = False
        if started:
            self._shutdown()

    def __del__(self):
        try:
            self.close()
        except Exception as e:  # noqa: BLE001 — interpreter-teardown best effort
            logging.getLogger(__name__).debug(
                "DataPipeline teardown: %r", e)

    # -- reader workers ------------------------------------------------------
    def _claim(self, wid):
        """Next shard position for worker ``wid``: its own slice of the
        order first (``pos % workers == wid`` — zero contention while
        the pool is healthy), else the earliest eligible position (the
        steal path that absorbs a dead peer's slice).  Only positions
        inside the in-flight window are claimable — the backpressure
        bound.  None = no work will ever remain."""
        with self._cond:
            while True:
                if self._stop.is_set():
                    return None
                hi = min(len(self._jobs), self._base + self._max_inflight)
                eligible = [p for p in range(self._base, hi)
                            if self._jobs[p].state == "pending"]
                if eligible:
                    own = [p for p in eligible
                           if p % self._workers == wid]
                    p = own[0] if own else eligible[0]
                    job = self._jobs[p]
                    job.state = "active"
                    job.owner = wid
                    return p, job
                if all(j.state in ("produced", "consumed")
                       for j in self._jobs):
                    return None
                self._cond.wait(timeout=0.1)

    def _put(self, job, item, stop):
        while True:
            try:
                job.queue.put(item, timeout=0.1)
                break
            except _queue.Full:
                if stop.is_set():
                    raise _Shutdown() from None
        if item is not _END_OF_SHARD:
            with self._cond:
                self._buffered += 1
                depth = self._buffered
            self._note_progress(depth)

    def _note_progress(self, depth):
        from . import telemetry as _telemetry
        self._last_progress = time.monotonic()
        _telemetry.record_data_queue_depth(depth)

    def _reader(self, wid, stop):
        from . import telemetry as _telemetry
        from .chaos.failpoints import failpoint as _failpoint
        pos = None
        try:
            while True:
                claimed = self._claim(wid)
                if claimed is None:
                    return
                pos, job = claimed
                for batch in self._source.read_shard(job.shard,
                                                     start=job.delivered):
                    # the chaos reader site: delay = slow reader,
                    # raise = this reader dies and its work rebalances
                    _failpoint("io/reader/read")
                    self._put(job, batch, stop)
                    job.delivered += 1
                    _telemetry.record_data_batches(1)
                self._put(job, _END_OF_SHARD, stop)
                with self._cond:
                    job.state = "produced"
                    self._cond.notify_all()
        except _Shutdown:
            return
        except BaseException as e:  # noqa: BLE001 — any reader fault rebalances
            self._on_reader_death(wid, pos, e)

    def _on_reader_death(self, wid, pos, exc):
        from . import telemetry as _telemetry
        with self._cond:
            self._live -= 1
            if pos is not None and self._jobs[pos].state == "active" \
                    and self._jobs[pos].owner == wid:
                # requeue the in-progress shard; ``delivered`` makes the
                # next owner resume at the first undelivered batch —
                # exactly-once.  The dead worker's untouched slice needs
                # nothing: survivors steal it position by position.
                self._jobs[pos].state = "pending"
                self._jobs[pos].owner = None
            unfinished = any(j.state not in ("produced", "consumed")
                             for j in self._jobs)
            if self._live <= 0 and unfinished:
                self._fatal = exc
            self._cond.notify_all()
        _telemetry.record_data_rebalance()

    # -- the assembler (consumer side) --------------------------------------
    def next(self):
        from . import telemetry as _telemetry
        self._start()  # idempotent: no-op once this epoch is running
        with self._cond:
            serial = self._serial
        if serial is not None:
            return next(serial)
        t0 = time.perf_counter()
        try:
            while True:
                with self._cond:
                    if self._pos >= len(self._jobs):
                        raise StopIteration
                    job = self._jobs[self._pos]
                    inline = job.inline
                if inline is not None:
                    # assembler rescue: this position's reader is gone
                    # and nobody claimed it — read it in-thread so the
                    # epoch keeps moving (never a stall)
                    try:
                        batch = next(inline)
                    except StopIteration:
                        self._consume_job(job)
                        continue
                    _telemetry.record_data_batches(1)
                    self._note_progress(self.buffered())
                    return batch
                try:
                    item = job.queue.get(timeout=0.05)
                except _queue.Empty:
                    self._on_starved(job)
                    continue
                job.idle_polls = 0
                if item is _END_OF_SHARD:
                    self._consume_job(job)
                    continue
                with self._cond:
                    self._buffered -= 1
                    depth = self._buffered
                self._note_progress(depth)
                return item
        finally:
            # graftlint: disable=raw-phase-timing -- this IS telemetry's collection point for the data_wait lane
            _telemetry.record_data_wait(time.perf_counter() - t0)

    def _consume_job(self, job):
        with self._cond:
            job.state = "consumed"
            job.inline = None
            self._pos += 1
            self._base = self._pos
            self._cond.notify_all()

    def _on_starved(self, job):
        """The head-of-line queue timed out.  Three cases: the pool is
        entirely dead (typed error — never a silent stall), the head
        position has an owner (it is producing or briefly scheduled —
        keep waiting), or it is ownerless and stayed that way across
        two polls while every survivor is busy elsewhere (claim it for
        the assembler and read it inline)."""
        with self._cond:
            if self._fatal is not None and job.queue.empty() \
                    and job.state != "produced":
                raise DataReaderError(
                    f"all {self._workers} data reader workers died "
                    f"(epoch {self._epoch}, shard position {self._pos}"
                    f"/{len(self._jobs)})") from self._fatal
            if job.state == "pending":
                job.idle_polls += 1
                if job.idle_polls >= 2:
                    job.state = "active"
                    job.owner = -1
                    job.inline = self._drain_then_read(job)

    def _drain_then_read(self, job):
        # leftovers a dead owner already queued come first (order), then
        # read from the delivered watermark — exactly-once either way
        try:
            while True:
                item = job.queue.get_nowait()
                if item is _END_OF_SHARD:
                    return
                with self._cond:
                    self._buffered -= 1
                yield item
        except _queue.Empty:
            pass
        for batch in self._source.read_shard(job.shard,
                                             start=job.delivered):
            job.delivered += 1
            yield batch

    def buffered(self):
        """Batches currently queued (the backpressure bound under
        test: <= max_inflight * queue_depth)."""
        with self._cond:
            return self._buffered


# -- window feed (stage half of the stage/dispatch thread pair) --------------
class WindowFeed:
    """Collect-and-stage thread for the scanned fit loop.

    Pulls batches from ``data_iter`` (any iterator — a
    :class:`DataPipeline` assembler or a plain DataIter), groups them
    into W-batch windows exactly like ``Module._fit_epoch_scan_inner``
    .collect(), and runs ``io.stage_super_batch`` OFF the train
    thread.  A 2-deep bounded queue double-buffers: window N+1 is
    collected and staged while window N's scan executes.  Items:

    * ``("window", batches, sbatch, (t0, t1))`` — a full staged window
      (raw batches ride along for the per-batch fallback path);
    * ``("fallback", batches, None, (t0, t1))`` — a short or
      shape-mismatched group that must run per-batch;
    * ``("end", ...)`` — upstream exhausted;
    * ``("error", exc, ...)`` — upstream raised; re-raised on the
      train thread.
    """

    def __init__(self, data_iter, window, ctx, batch_ok, depth=2,
                 host=False):
        self._iter = iter(data_iter)
        self._window = int(window)
        self._ctx = ctx
        self._host = host
        self._batch_ok = batch_ok
        self._q = _queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="mx-window-feed", daemon=True)
        self._thread.start()

    def _run(self):
        from . import telemetry as _telemetry
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                batches, full = [], True
                ended = False
                while len(batches) < self._window:
                    try:
                        b = next(self._iter)
                    except StopIteration:
                        ended = True
                        break
                    batches.append(b)
                    if not self._batch_ok(b):
                        full = False
                        break
                span = (t0, time.perf_counter())
                if len(batches) == self._window and full:
                    sbatch = mx_io.stage_super_batch(batches, self._ctx,
                                                     host=self._host)
                    _telemetry.record_data_queue_depth(
                        self._q.qsize() + 1, role="feed")
                    self._put(("window", batches, sbatch, span))
                elif batches:
                    self._put(("fallback", batches, None, span))
                if ended:
                    self._put(("end", None, None, None))
                    return
        except _Shutdown:
            pass
        except BaseException as e:  # noqa: BLE001 — surfaced on the train thread
            try:
                self._put(("error", e, None, None))
            except _Shutdown:
                pass

    def _put(self, item):
        while True:
            try:
                self._q.put(item, timeout=0.1)
                return
            except _queue.Full:
                if self._stop.is_set():
                    raise _Shutdown() from None

    def get(self):
        """Next item, blocking; the caller charges the blocked time to
        the ``data_wait`` lane (it wraps this call)."""
        from . import telemetry as _telemetry
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=0.5)
                break
            except _queue.Empty:
                if not self._thread.is_alive():
                    # feed thread died without an item: surface typed
                    # rather than spin forever
                    raise DataReaderError(
                        "window-feed staging thread died") from None
        # graftlint: disable=raw-phase-timing -- this IS telemetry's collection point for the data_wait lane
        _telemetry.record_data_wait(time.perf_counter() - t0)
        if item[0] == "error":
            raise item[1]
        return item

    def close(self):
        self._stop.set()
        while self._thread.is_alive():
            try:
                while True:
                    self._q.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=0.2)


def feed_enabled():
    """Whether the fit loop should stage windows off-thread
    (``MXNET_DATA_WORKERS > 0`` — one knob arms both halves of the
    data plane)."""
    from . import config as _config
    return int(_config.get("MXNET_DATA_WORKERS")) > 0


# -- smoke -------------------------------------------------------------------
def _smoke():
    """CI gate: order determinism across worker counts, exactly-once
    under a mid-epoch reader death, and the backpressure bound."""
    from .chaos import failpoints as _fp

    rng = np.random.RandomState(7)
    x = rng.rand(64 * 4, 5).astype(np.float32)
    y = rng.rand(64 * 4, 1).astype(np.float32)

    def seq(workers, **kw):
        src = NDArraySource(x, y, batch_size=4, batches_per_shard=2)
        pipe = DataPipeline(src, workers=workers, queue_depth=2, seed=3,
                            **kw)
        out = []
        for b in pipe:
            out.append(np.concatenate([a.asnumpy().ravel()
                                       for a in b.data + b.label]))
        pipe.close()
        return out

    base = seq(0)
    assert len(base) == 64, len(base)
    for w in (1, 2, 4):
        got = seq(w)
        assert len(got) == len(base) and \
            all(np.array_equal(a, b) for a, b in zip(base, got)), \
            f"shard order diverged at workers={w}"

    # one reader dies mid-epoch: every batch still arrives exactly once
    _fp.arm("io/reader/read", "raise", hits=13, count=1)
    try:
        got = seq(2)
    finally:
        _fp.disarm("io/reader/read")
    assert len(got) == len(base) and \
        all(np.array_equal(a, b) for a, b in zip(base, got)), \
        "dead-reader rebalance lost or duplicated batches"

    # stalled consumer: buffered batches stay inside the bound
    src = NDArraySource(x, y, batch_size=4, batches_per_shard=2)
    pipe = DataPipeline(src, workers=2, queue_depth=2, seed=3)
    next(pipe)
    time.sleep(0.5)
    bound = pipe._max_inflight * pipe._depth
    assert pipe.buffered() <= bound, (pipe.buffered(), bound)
    pipe.close()
    print("io_pipeline smoke OK: determinism x {0,1,2,4} workers, "
          "exactly-once under reader death, backpressure bound",
          flush=True)


if __name__ == "__main__":
    _smoke()
