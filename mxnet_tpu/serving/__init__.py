"""mxnet_tpu.serving — continuously-batched replica-pool serving.

The L-layer above the executor that the ROADMAP's "serves heavy traffic"
north star needs: a versioned ModelRepository (hot reload, multi-model),
a compiled-executor cache with shape bucketing (measured ladders from
mxnet_tpu.compile's BucketPlanner, power-of-two before any traffic;
repeated shapes reuse one XLA program, padding handled transparently,
publish-time AOT warmup — see docs/compile.md), a DynamicBatcher that
batches CONTINUOUSLY (cohort-aware admission into the forming
micro-batch, stage/dispatch pipelining so batch N+1 coalesces while N
executes) under a max_batch_size / max_latency_ms deadline policy, and
a ReplicaPool router scaling each model endpoint across K batcher
replicas — load-aware routing on occupancy x drain-time EWMA, graceful
spill to siblings, predicted-p99 SLO admission control, and
drain-on-removal — with load shedding, per-request timeouts, graceful
drain, and p50/p90/p99 serving metrics exported through the profiler
counter lanes and the telemetry registry.  See docs/serving.md.

ISSUE 16 adds the STATEFUL half: ``generation``/``kv_cache`` hold
autoregressive sessions whose paged KV caches live on device across
micro-batches — slot-pool admission charged to the resource ledger,
anchor/join prefill cohorts interleaved with one fixed-shape jit decode
step per micro-batch, and a content-hash prefix cache for shared prompt
heads (``ModelServer.load_generator`` / ``generate``).
"""
from .batcher import (CohortQueue, DynamicBatcher, RequestTimeoutError,
                      ServeFuture, ServingClosedError,
                      ServingOverloadError, ServingWorkerError)
from .executor_cache import (CachedExecutor, ExecutorCache,
                             bind_inference_executor, bucket_batch,
                             feed_signature, pad_to, shape_signature,
                             shared_cache)
from .generation import (GenerationEngine, GenerationModel,
                         GenerationSession, tiny_lm)
from .kv_cache import KVPoolExhaustedError, KVSlotPool, PrefixCache
from .metrics import ServingMetrics, stats
from .repository import ModelRepository
from .router import AdmissionController, ReplicaPool
from .server import ModelServer

__all__ = [
    "AdmissionController", "CachedExecutor", "CohortQueue",
    "DynamicBatcher", "ExecutorCache",
    "GenerationEngine", "GenerationModel", "GenerationSession",
    "KVPoolExhaustedError", "KVSlotPool", "ModelRepository",
    "ModelServer", "PrefixCache", "ReplicaPool", "RequestTimeoutError",
    "ServeFuture", "ServingClosedError",
    "ServingMetrics", "ServingOverloadError", "ServingWorkerError",
    "bind_inference_executor",
    "bucket_batch", "feed_signature", "pad_to", "shape_signature",
    "shared_cache", "stats", "tiny_lm",
]
