"""mxnet_tpu.serving — dynamic-batching inference serving.

The L-layer above the executor that the ROADMAP's "serves heavy traffic"
north star needs: a versioned ModelRepository (hot reload, multi-model),
a compiled-executor cache with shape bucketing (measured ladders from
mxnet_tpu.compile's BucketPlanner, power-of-two before any traffic;
repeated shapes reuse one XLA program, padding handled transparently,
publish-time AOT warmup — see docs/compile.md), and a
DynamicBatcher draining a bounded queue under a max_batch_size /
max_latency_ms deadline policy — with load shedding, per-request
timeouts, graceful drain, and p50/p90/p99 serving metrics exported
through the profiler counter lanes.  See docs/serving.md.
"""
from .batcher import (DynamicBatcher, RequestTimeoutError, ServeFuture,
                      ServingClosedError, ServingOverloadError,
                      ServingWorkerError)
from .executor_cache import (CachedExecutor, ExecutorCache,
                             bind_inference_executor, bucket_batch,
                             feed_signature, pad_to, shape_signature,
                             shared_cache)
from .metrics import ServingMetrics, stats
from .repository import ModelRepository
from .server import ModelServer

__all__ = [
    "CachedExecutor", "DynamicBatcher", "ExecutorCache", "ModelRepository",
    "ModelServer", "RequestTimeoutError", "ServeFuture", "ServingClosedError",
    "ServingMetrics", "ServingOverloadError", "ServingWorkerError",
    "bind_inference_executor",
    "bucket_batch", "feed_signature", "pad_to", "shape_signature",
    "shared_cache", "stats",
]
