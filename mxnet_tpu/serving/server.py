"""ModelServer: repository + executor cache + per-model replica pools.

The in-process serving front end:

    server = mx.serving.ModelServer()
    server.load("mlp", block=net)              # or prefix= / symbol=+params=
    out = server.predict("mlp", {"data": x})   # x: one sample, no batch dim
    fut = server.predict_async("mlp", {"data": x})
    server.resize("mlp", 4)                    # scale the replica pool
    server.stats()                             # metrics snapshot
    server.shutdown()                          # graceful drain

Each model endpoint is a :class:`ReplicaPool` (``MXNET_SERVING_REPLICAS``
batcher replicas behind load-aware routing, graceful spill and SLO
admission control — see router.py); a pool of 1 behaves exactly like the
PR-1 single batcher.  Execution path per micro-batch (one per dispatch
pass, see batcher.py): resolve the LATEST model version from the
repository (this is what makes ``load`` a hot reload), bucket the batch
to the planned ladder (or next power of two), fetch the bound executor
from the LRU cache — (model, version, signature) key, compile only on
first use — pad, forward, unpad, fan results back out to the request
futures.  On a checkpoint hot-swap the repository's flip hook retires
stale-version executors from the cache and resets the pool's admission
EWMA, so the pool re-learns the new version's service rate instead of
shedding (or admitting) on the old one's.
"""
from __future__ import annotations

import threading

import numpy as np

from .. import compile as _compile
from ..base import MXNetError
from ..context import current_context
from ..telemetry import flight as _flight
from ..telemetry import trace as _trace
from .executor_cache import (ExecutorCache, bind_inference_executor,
                             bucket_batch, feed_signature, pad_to)
from .metrics import ServingMetrics
from .repository import ModelRepository
from .router import ReplicaPool


class ModelServer:
    """Multi-model in-process inference server."""

    def __init__(self, repository=None, ctx=None, max_batch_size=None,
                 max_latency_ms=None, num_workers=None, max_queue_depth=None,
                 shed_watermark=None, default_timeout_ms=None,
                 cache_capacity=None, num_replicas=None, slo_p99_ms=None,
                 name="server"):
        from .. import config as _config
        self.name = name
        self.repository = repository or ModelRepository()
        self._ctx = ctx or current_context()
        self._cache = ExecutorCache(cache_capacity)
        self.metrics = ServingMetrics(name)
        self._max_batch = int(max_batch_size if max_batch_size is not None
                              else _config.get("MXNET_SERVING_MAX_BATCH"))
        self._num_replicas = num_replicas
        self._slo_p99_ms = slo_p99_ms
        self._batcher_kw = dict(
            max_batch_size=self._max_batch, max_latency_ms=max_latency_ms,
            num_workers=num_workers, max_queue_depth=max_queue_depth,
            shed_watermark=shed_watermark,
            default_timeout_ms=default_timeout_ms)
        self._pools = {}
        self._generators = {}   # name -> GenerationEngine (ISSUE 16)
        self._lock = threading.Lock()
        self._shutdown = False
        # publish-time ladder warmup: the repository calls back BEFORE a
        # hot-reloaded checkpoint version starts serving (and on a
        # background thread after an explicit hot-reload load)
        self.repository.add_warm_hook(self._warm_hook)
        # post-flip: retire stale-version executors, reset admission
        self.repository.add_flip_hook(self._flip_hook)

    # -- model management ---------------------------------------------------
    def load(self, name, **kwargs):
        """Load (or hot-reload) a model; see ModelRepository.load."""
        return self.repository.load(name, **kwargs)

    def unload(self, name, version=None):
        """Drop one version (or the whole model).  Unloading the whole
        model drains its replica pool first — admitted requests finish,
        late submits get ``ServingClosedError``."""
        self.repository.unload(name, version=version)
        if version is None:
            with self._lock:
                pool = self._pools.pop(name, None)
            if pool is not None:
                pool.close(drain=True)
        self._cache.evict_model((name,) if version is None
                                else (name, int(version)))

    # -- generation endpoints (ISSUE 16) ------------------------------------
    def load_generator(self, name, model, warm=False, **engine_kw):
        """Create (or hot-reload) a stateful generation endpoint.

        First call builds a :class:`~.generation.GenerationEngine`
        around ``model`` (a :class:`~.generation.GenerationModel`) and
        registers the payload as an opaque repository version, so the
        endpoint shows up in :meth:`ModelServer.stats`/``models()`` and
        rides the same flip-hook plumbing as Symbol models.  A later
        call with the same ``name`` is a hot reload: the engine builds
        and AOT-warms the NEW version's decode/prefill ladders before
        its served-version pointer flips (warm-before-flip — zero
        post-flip compiles), then the repository flip hook retires the
        stale version's executors, decode ladders and prefix-cache
        activations through the executor cache's retire hooks."""
        from .generation import GenerationEngine
        with self._lock:
            if self._shutdown:
                from .batcher import ServingClosedError
                raise ServingClosedError(self.name)
            eng = self._generators.get(name)
        if eng is None:
            eng = GenerationEngine(model, name=f"{self.name}/{name}",
                                   metrics=self.metrics, **engine_kw)
            with self._lock:
                self._generators[name] = eng
            # a flipped generation version must retire its ladders and
            # prefix activations exactly where stale executors retire
            self._cache.add_retire_hook(
                lambda m, keep, _eng=eng, _n=name:
                    _eng.retire_stale(keep) if m == _n else None)
            if warm:
                eng.warm()
            version = self.repository.register_opaque(name, model)
        else:
            version = eng.load(model, warm=True)  # warm-before-flip
            self.repository.register_opaque(name, model, version=version)
        return version

    def generator(self, name):
        """The live GenerationEngine behind ``name`` (KeyError when
        ``name`` is not a generation endpoint)."""
        with self._lock:
            return self._generators[name]

    def generate_async(self, model, prompt, **kw):
        """Start one streaming generation session (see
        GenerationEngine.start_session for admission semantics)."""
        return self.generator(model).start_session(prompt, **kw)

    def generate(self, model, prompt, timeout=None, **kw):
        """Blocking convenience: the full generated token list."""
        return self.generate_async(model, prompt, **kw).result(timeout)

    # -- the per-batch execution path ---------------------------------------
    def _runner_for(self, model):
        def run(feed, n_real):
            # latest-version resolution happens HERE, per batch: traffic
            # in flight during a hot reload finishes on the old version,
            # the next batch serves the new one
            mv = self.repository.get(model)
            missing = [n for n in mv.input_names if n not in feed]
            if missing:
                raise MXNetError(
                    f"serving[{model}]: request is missing inputs "
                    f"{missing} (expects {mv.input_names})")
            max_batch = self._max_batch
            # the measured workload the BucketPlanner plans from: formed
            # batch size + per-sample signature (warmup's shape source)
            feed_np = {k: np.asarray(v) for k, v in feed.items()}
            _compile.STATS.record_batch(model, n_real, feed_np)
            bucket = bucket_batch(n_real, max_batch,
                                  ladder=_compile.ladder_for(model))
            # request dtypes are preserved end to end (int token ids /
            # indices / masks must NOT be silently cast to float32);
            # the executor binds its input buffers with the same dtypes
            padded = {k: pad_to(v, bucket) for k, v in feed_np.items()}
            sig = feed_signature(padded)
            entry = self._cache.get(
                (model, mv.version, sig),
                lambda: bind_inference_executor(
                    mv.symbol, mv.params,
                    {k: v.shape for k, v in padded.items()}, self._ctx,
                    input_dtypes={k: v.dtype for k, v in padded.items()}),
                model=model)
            outs = entry.run_padded(padded, n_real)
            self.metrics.observe_batch(n_real, bucket)
            return outs
        return run

    def _validator_for(self, model):
        """Submit-time request validation: key-set check against the
        model's input names, then per-sample shape/dtype validation by
        graph inference (param shapes/dtypes are known exactly), cached
        per (version, signature).  Raising here rejects ONE request
        synchronously — it never reaches (or poisons) a batch."""
        valid_sigs = {}

        def validate(inputs):
            mv = self.repository.get(model)
            missing = [n for n in mv.input_names if n not in inputs]
            extra = [k for k in inputs if k not in mv.input_names]
            if missing or extra:
                raise MXNetError(
                    f"serving[{model}]: request inputs {sorted(inputs)} "
                    f"do not match model inputs {mv.input_names}"
                    + (f" — missing {missing}" if missing else "")
                    + (f" — unexpected {extra}" if extra else ""))
            sig = tuple(sorted((k, v.shape, v.dtype.str)
                               for k, v in inputs.items()))
            key = (mv.version, sig)
            if key in valid_sigs:
                return
            shapes = {k: tuple(p.shape) for k, p in mv.params.items()}
            shapes.update({k: (1,) + tuple(v.shape)
                           for k, v in inputs.items()})
            dtypes = {k: p.dtype for k, p in mv.params.items()}
            dtypes.update({k: v.dtype for k, v in inputs.items()})
            try:
                mv.symbol.infer_shape(**shapes)
                mv.symbol.infer_type(**dtypes)
            except Exception as e:  # noqa: BLE001 — structured per-request
                raise MXNetError(
                    f"serving[{model}]: request rejected — sample "
                    f"shapes/dtypes are incompatible with the model: "
                    f"{e}") from e
            valid_sigs[key] = True
        return validate

    # -- publish-time ladder warmup ------------------------------------------
    def _warm_max_batch(self, model):
        return self._max_batch

    def _warm_hook(self, model, mv):
        """Repository warm hook: compile the new version's full bucket
        ladder (planned from the measured histogram when enough traffic
        was observed) before it serves."""
        if mv.symbol is None:
            # opaque (generation) payload: the engine AOT-warms its
            # decode/prefill ladders synchronously in load_generator,
            # before the version registers — nothing to do here
            return
        _compile.warm_version(self._cache, model, mv, self._ctx,
                              self._warm_max_batch(model))

    def _flip_hook(self, model, mv, prev_latest):
        """Repository flip hook (runs AFTER the served-version pointer
        moved to ``mv``): retire executors for versions older than the
        previous one from the LRU — in-flight batches keep their bound
        references, so nothing they use is torn down — and reset the
        pool's admission EWMA so SLO shedding re-learns the NEW
        version's service rate instead of trusting the old one's."""
        self._cache.evict_stale_versions(model, {mv.version, prev_latest})
        with self._lock:
            pool = self._pools.get(model)
        if pool is not None:
            pool.admission.reset()
        _flight.record("serving", "version_flip", model=model,
                       version=mv.version, prev=prev_latest)

    def warm(self, model, version=None, sample_signature=None,
             ladder=None):
        """Explicitly warm ``model``'s bucket ladder: plan (or take)
        the ladder, bind + AOT-compile every bucket into the executor
        cache, and mark the signatures warmed so later retraces alarm.

        ``sample_signature``: iterable of (input_name, sample_shape,
        dtype_str) — defaults to the most common signature observed in
        traffic.  Returns the list of warmed bucket sizes.

        A generation endpoint warms its OWN ladder family — the decode
        step plus every prefill prompt bucket — through the same entry
        point."""
        with self._lock:
            eng = self._generators.get(model)
        if eng is not None:
            return eng.warm(version=version)
        mv = self.repository.get(model, version=version)
        if sample_signature is not None:
            sample_signature = tuple(sorted(
                (str(n), tuple(int(d) for d in s), str(d_))
                for n, s, d_ in sample_signature))
        return _compile.warm_version(
            self._cache, model, mv, self._ctx,
            self._warm_max_batch(model),
            sample_signature=sample_signature, ladder=ladder)

    def _get_pool(self, model):
        with self._lock:
            if self._shutdown:
                from .batcher import ServingClosedError
                raise ServingClosedError(self.name)
            pool = self._pools.get(model)
            if pool is None:
                # metrics are shared server-wide; per-model split lives in
                # the (model, …) executor-cache keys, pool names and the
                # {model}-labelled router telemetry families
                runner = self._runner_for(model)
                pool = ReplicaPool(
                    lambda rid: runner,
                    num_replicas=self._num_replicas,
                    name=f"{self.name}/{model}", model=model,
                    metrics=self.metrics,
                    validator=self._validator_for(model),
                    slo_p99_ms=self._slo_p99_ms,
                    **self._batcher_kw)
                self._pools[model] = pool
            return pool

    def resize(self, model, num_replicas, drain=True):
        """Scale ``model``'s replica pool up or down (shrinking drains
        the removed replicas — zero admitted requests dropped)."""
        self._get_pool(model).resize(num_replicas, drain=drain)

    # -- request API --------------------------------------------------------
    def predict_async(self, model, inputs, timeout_ms=None):
        """Submit one request (single sample, batch dim added by the
        batcher); returns a ServeFuture of the output list.

        With ``MXNET_TRACE`` on, a trace context is minted HERE and
        rides the request end to end — submit stage, admission verdict,
        route choice, spill hops, queue/stage/dispatch/resolve spans —
        one trace per request regardless of how many replicas it
        visited (docs/observability.md trace taxonomy)."""
        tr = _trace.start("serving", model)
        try:
            with tr.stage("submit"):
                self.repository.get(model)  # unknown-model errors here
                return self._get_pool(model).submit(
                    dict(inputs), timeout_ms=timeout_ms, trace=tr)
        except BaseException as e:
            # refused synchronously (shed / closed / invalid): the
            # trace still finishes, typed — sheds are traceable too;
            # finish under finally so even a failing event() cannot
            # leak the span into the tracer's active set
            try:
                tr.event("rejected", error=type(e).__name__)
            finally:
                tr.finish(status="rejected")
            raise

    def predict(self, model, inputs, timeout_ms=None, wait_s=60.0):
        """Blocking convenience over predict_async."""
        return self.predict_async(model, inputs,
                                  timeout_ms=timeout_ms).result(wait_s)

    # -- observability / lifecycle ------------------------------------------
    def stats(self):
        snap = self.metrics.snapshot()
        snap["executor_cache"] = self._cache.stats()
        snap["models"] = self.repository.models()
        with self._lock:
            pools = dict(self._pools)
            generators = dict(self._generators)
        snap["pools"] = {model: pool.stats()
                         for model, pool in pools.items()}
        snap["generators"] = {name: eng.stats()
                              for name, eng in generators.items()}
        return snap

    def shutdown(self, drain=True, timeout=30.0):
        """Stop intake on every pool; drain in-flight work (default)
        or fail it fast; idempotent."""
        with self._lock:
            self._shutdown = True
            pools = list(self._pools.values())
            generators = list(self._generators.values())
        for eng in generators:
            eng.close(timeout=timeout)
        for pool in pools:
            pool.close(drain=drain, timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
