"""ModelServer: repository + executor cache + per-model dynamic batchers.

The in-process serving front end:

    server = mx.serving.ModelServer()
    server.load("mlp", block=net)              # or prefix= / symbol=+params=
    out = server.predict("mlp", {"data": x})   # x: one sample, no batch dim
    fut = server.predict_async("mlp", {"data": x})
    server.stats()                             # metrics snapshot
    server.shutdown()                          # graceful drain

Execution path per batch (one per worker pass, see batcher.py): resolve
the LATEST model version from the repository (this is what makes
``load`` a hot reload), bucket the batch to the next power of two, fetch
the bound executor from the LRU cache — (model, version, signature) key,
compile only on first use — pad, forward, unpad, fan results back out to
the request futures.
"""
from __future__ import annotations

import threading

import numpy as np

from .. import compile as _compile
from ..base import MXNetError
from ..context import current_context
from .batcher import DynamicBatcher
from .executor_cache import (ExecutorCache, bind_inference_executor,
                             bucket_batch, feed_signature, pad_to)
from .metrics import ServingMetrics
from .repository import ModelRepository


class ModelServer:
    """Multi-model in-process inference server."""

    def __init__(self, repository=None, ctx=None, max_batch_size=None,
                 max_latency_ms=None, num_workers=None, max_queue_depth=None,
                 shed_watermark=None, default_timeout_ms=None,
                 cache_capacity=None, name="server"):
        self.name = name
        self.repository = repository or ModelRepository()
        self._ctx = ctx or current_context()
        self._cache = ExecutorCache(cache_capacity)
        self.metrics = ServingMetrics(name)
        self._batcher_kw = dict(
            max_batch_size=max_batch_size, max_latency_ms=max_latency_ms,
            num_workers=num_workers, max_queue_depth=max_queue_depth,
            shed_watermark=shed_watermark,
            default_timeout_ms=default_timeout_ms)
        self._batchers = {}
        self._lock = threading.Lock()
        self._shutdown = False
        # publish-time ladder warmup: the repository calls back BEFORE a
        # hot-reloaded checkpoint version starts serving (and on a
        # background thread after an explicit hot-reload load)
        self.repository.add_warm_hook(self._warm_hook)

    # -- model management ---------------------------------------------------
    def load(self, name, **kwargs):
        """Load (or hot-reload) a model; see ModelRepository.load."""
        return self.repository.load(name, **kwargs)

    def unload(self, name, version=None):
        self.repository.unload(name, version=version)
        self._cache.evict_model((name,) if version is None
                                else (name, int(version)))

    # -- the per-batch execution path ---------------------------------------
    def _runner_for(self, model):
        def run(feed, n_real):
            # latest-version resolution happens HERE, per batch: traffic
            # in flight during a hot reload finishes on the old version,
            # the next batch serves the new one
            mv = self.repository.get(model)
            missing = [n for n in mv.input_names if n not in feed]
            if missing:
                raise MXNetError(
                    f"serving[{model}]: request is missing inputs "
                    f"{missing} (expects {mv.input_names})")
            # _batchers is guarded by _lock (a concurrent _get_batcher
            # may be resizing the dict); max_batch_size itself is
            # immutable after construction
            with self._lock:
                max_batch = self._batchers[model].max_batch_size
            # the measured workload the BucketPlanner plans from: formed
            # batch size + per-sample signature (warmup's shape source)
            feed_np = {k: np.asarray(v) for k, v in feed.items()}
            _compile.STATS.record_batch(model, n_real, feed_np)
            bucket = bucket_batch(n_real, max_batch,
                                  ladder=_compile.ladder_for(model))
            # request dtypes are preserved end to end (int token ids /
            # indices / masks must NOT be silently cast to float32);
            # the executor binds its input buffers with the same dtypes
            padded = {k: pad_to(v, bucket) for k, v in feed_np.items()}
            sig = feed_signature(padded)
            entry = self._cache.get(
                (model, mv.version, sig),
                lambda: bind_inference_executor(
                    mv.symbol, mv.params,
                    {k: v.shape for k, v in padded.items()}, self._ctx,
                    input_dtypes={k: v.dtype for k, v in padded.items()}),
                model=model)
            outs = entry.run_padded(padded, n_real)
            self.metrics.observe_batch(n_real, bucket)
            return outs
        return run

    def _validator_for(self, model):
        """Submit-time request validation: key-set check against the
        model's input names, then per-sample shape/dtype validation by
        graph inference (param shapes/dtypes are known exactly), cached
        per (version, signature).  Raising here rejects ONE request
        synchronously — it never reaches (or poisons) a batch."""
        valid_sigs = {}

        def validate(inputs):
            mv = self.repository.get(model)
            missing = [n for n in mv.input_names if n not in inputs]
            extra = [k for k in inputs if k not in mv.input_names]
            if missing or extra:
                raise MXNetError(
                    f"serving[{model}]: request inputs {sorted(inputs)} "
                    f"do not match model inputs {mv.input_names}"
                    + (f" — missing {missing}" if missing else "")
                    + (f" — unexpected {extra}" if extra else ""))
            sig = tuple(sorted((k, v.shape, v.dtype.str)
                               for k, v in inputs.items()))
            key = (mv.version, sig)
            if key in valid_sigs:
                return
            shapes = {k: tuple(p.shape) for k, p in mv.params.items()}
            shapes.update({k: (1,) + tuple(v.shape)
                           for k, v in inputs.items()})
            dtypes = {k: p.dtype for k, p in mv.params.items()}
            dtypes.update({k: v.dtype for k, v in inputs.items()})
            try:
                mv.symbol.infer_shape(**shapes)
                mv.symbol.infer_type(**dtypes)
            except Exception as e:  # noqa: BLE001 — structured per-request
                raise MXNetError(
                    f"serving[{model}]: request rejected — sample "
                    f"shapes/dtypes are incompatible with the model: "
                    f"{e}") from e
            valid_sigs[key] = True
        return validate

    # -- publish-time ladder warmup ------------------------------------------
    def _warm_max_batch(self, model):
        with self._lock:
            b = self._batchers.get(model)
        if b is not None:
            return b.max_batch_size
        mb = self._batcher_kw.get("max_batch_size")
        if mb is None:
            from .. import config as _config
            mb = _config.get("MXNET_SERVING_MAX_BATCH")
        return int(mb)

    def _warm_hook(self, model, mv):
        """Repository warm hook: compile the new version's full bucket
        ladder (planned from the measured histogram when enough traffic
        was observed) before it serves."""
        _compile.warm_version(self._cache, model, mv, self._ctx,
                              self._warm_max_batch(model))

    def warm(self, model, version=None, sample_signature=None,
             ladder=None):
        """Explicitly warm ``model``'s bucket ladder: plan (or take)
        the ladder, bind + AOT-compile every bucket into the executor
        cache, and mark the signatures warmed so later retraces alarm.

        ``sample_signature``: iterable of (input_name, sample_shape,
        dtype_str) — defaults to the most common signature observed in
        traffic.  Returns the list of warmed bucket sizes."""
        mv = self.repository.get(model, version=version)
        if sample_signature is not None:
            sample_signature = tuple(sorted(
                (str(n), tuple(int(d) for d in s), str(d_))
                for n, s, d_ in sample_signature))
        return _compile.warm_version(
            self._cache, model, mv, self._ctx,
            self._warm_max_batch(model),
            sample_signature=sample_signature, ladder=ladder)

    def _get_batcher(self, model):
        with self._lock:
            if self._shutdown:
                from .batcher import ServingClosedError
                raise ServingClosedError(self.name)
            b = self._batchers.get(model)
            if b is None:
                # metrics are shared server-wide; per-model split lives in
                # the (model, …) executor-cache keys and batcher names
                b = DynamicBatcher(
                    self._runner_for(model), name=f"{self.name}/{model}",
                    metrics=self.metrics,
                    validator=self._validator_for(model),
                    **self._batcher_kw)
                self._batchers[model] = b
            return b

    # -- request API --------------------------------------------------------
    def predict_async(self, model, inputs, timeout_ms=None):
        """Submit one request (single sample, batch dim added by the
        batcher); returns a ServeFuture of the output list."""
        self.repository.get(model)  # unknown-model errors surface here
        return self._get_batcher(model).submit(dict(inputs),
                                               timeout_ms=timeout_ms)

    def predict(self, model, inputs, timeout_ms=None, wait_s=60.0):
        """Blocking convenience over predict_async."""
        return self.predict_async(model, inputs,
                                  timeout_ms=timeout_ms).result(wait_s)

    # -- observability / lifecycle ------------------------------------------
    def stats(self):
        snap = self.metrics.snapshot()
        snap["executor_cache"] = self._cache.stats()
        snap["models"] = self.repository.models()
        return snap

    def shutdown(self, drain=True, timeout=30.0):
        """Stop intake on every batcher; drain in-flight work (default)
        or fail it fast; idempotent."""
        with self._lock:
            self._shutdown = True
            batchers = list(self._batchers.values())
        for b in batchers:
            b.close(drain=drain, timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
