"""ModelRepository: versioned model storage behind the server.

A repository maps ``name -> {version -> _ModelVersion}``.  Versions are
monotonically increasing integers; ``get(name)`` returns the latest, so
loading a new version is a hot reload — in-flight batches finish on the
version they resolved, the next batch picks up the new one (the serving
runner resolves the version per batch, never per process).

Three load sources, all normalized to (Symbol, flat name->NDArray
params, input names):

* ``prefix``      — ``{prefix}-symbol.json`` + ``{prefix}-{epoch:04d}.params``
                    checkpoint pairs as written by ``HybridBlock.export`` /
                    ``Module.save_checkpoint``;
* ``symbol`` + ``params`` — an in-memory Symbol (or its JSON) plus a
                    param dict or raw ``.params`` bytes;
* ``block``       — a gluon (Hybrid)Block, traced to a Symbol graph and
                    its ``collect_params()`` snapshot.
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from ..chaos.failpoints import failpoint as _failpoint


def _strip_prefixes(param_dict):
    """arg:/aux: save-format prefixes -> flat names."""
    return {k.split(":", 1)[-1]: v for k, v in param_dict.items()}


class _ModelVersion:
    __slots__ = ("symbol", "params", "input_names", "version")

    def __init__(self, symbol, params, input_names, version):
        self.symbol = symbol
        self.params = params
        self.input_names = input_names
        self.version = version


def _normalize(symbol=None, params=None, prefix=None, block=None, epoch=0):
    from .. import ndarray as nd
    from ..symbol import load_json
    from ..symbol.symbol import Symbol

    if sum(x is not None for x in (symbol, prefix, block)) != 1:
        raise MXNetError(
            "repository.load: pass exactly one of symbol=, prefix=, block=")

    if prefix is not None:
        with open(f"{prefix}-symbol.json") as f:
            symbol = load_json(f.read())
        params = _strip_prefixes(nd.load(f"{prefix}-{epoch:04d}.params"))
    elif block is not None:
        # trace the block to a Symbol graph (same path as export, minus
        # the filesystem round trip)
        if not getattr(block, "_cached_graph", None):
            block._build_sym_graph()
        _, symbol = block._cached_graph
        params = {name: p._reduce()
                  for name, p in block.collect_params().items()}
    else:
        if isinstance(symbol, str):
            symbol = load_json(symbol)
        if not isinstance(symbol, Symbol):
            raise MXNetError(
                f"repository.load: symbol must be a Symbol or its JSON, "
                f"got {type(symbol).__name__}")
        if isinstance(params, (bytes, bytearray)):
            from ..c_predict import _load_params_bytes
            params = _load_params_bytes(bytes(params))
        elif isinstance(params, dict):
            params = _strip_prefixes(params)
        else:
            raise MXNetError(
                "repository.load: params must be a dict or .params bytes "
                "when loading from a symbol")

    bound = set(params)
    input_names = [n for n in symbol.list_arguments() if n not in bound]
    if not input_names:
        raise MXNetError(
            "repository.load: every argument is covered by params — the "
            "model has no free inputs to serve")
    return symbol, params, input_names


class ModelRepository:
    """Thread-safe versioned model store (multi-model endpoints)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._models = {}   # name -> {version -> _ModelVersion}
        self._latest = {}   # name -> int
        self._watchers = {}  # name -> (thread, stop Event)
        self._warm_hooks = []  # fn(name, _ModelVersion), pre-flip
        self._flip_hooks = []  # fn(name, _ModelVersion, prev_latest)
        # steps that failed checksum verification during poll_checkpoint,
        # quarantined so the watcher never re-reads a known-corrupt step
        # every poll interval: {(name, ckpt_dir): {step, ...}}
        self._corrupt_steps = {}

    # -- publish-time warmup hooks ------------------------------------------
    def add_warm_hook(self, fn):
        """Register ``fn(name, model_version)`` to run before a new
        version serves traffic: synchronously BEFORE the served-version
        pointer flips on checkpoint hot-reload (``watch``/
        ``poll_checkpoint``), and on a background thread after a
        hot-reload ``load``.  A hook failure is logged, never fatal —
        warming is an optimization, the flip must happen regardless."""
        with self._lock:
            self._warm_hooks.append(fn)
        return fn

    def _run_warm_hooks(self, name, mv):
        import logging
        from .. import config as _config
        if not _config.get("MXNET_COMPILE_WARMUP"):
            return
        with self._lock:
            hooks = list(self._warm_hooks)
        for fn in hooks:
            try:
                _failpoint("serving/repository/warm_hook")
                fn(name, mv)
            except Exception:  # warm failure must never block the flip
                logging.getLogger("mxnet_tpu.serving").exception(
                    "warm hook %r failed for %s v%s", fn, name,
                    mv.version)

    def add_flip_hook(self, fn):
        """Register ``fn(name, model_version, prev_latest)`` to run
        right AFTER a hot-reload moves the served-version pointer (the
        drain+rebuild hook: the server uses it to retire stale-version
        executors from the LRU and reset the pool's SLO admission EWMA
        so it re-learns the new version's service rate).  Failures are
        logged, never fatal — the flip already happened."""
        with self._lock:
            self._flip_hooks.append(fn)
        return fn

    def _run_flip_hooks(self, name, mv, prev_latest):
        import logging
        with self._lock:
            hooks = list(self._flip_hooks)
        for fn in hooks:
            try:
                fn(name, mv, prev_latest)
            except Exception:  # the flip is already live; never unwind it
                logging.getLogger("mxnet_tpu.serving").exception(
                    "flip hook %r failed for %s v%s", fn, name,
                    mv.version)

    def _register(self, name, mv):
        """Make ``mv`` visible (the pointer flip).  Allocates latest+1
        when ``mv.version`` is None; raises on an explicit-version
        collision.  Returns (version, was_hot_reload, prev_latest)."""
        with self._lock:
            versions = self._models.setdefault(name, {})
            was_loaded = bool(versions)
            prev_latest = self._latest.get(name, 0)
            if mv.version is None:
                mv.version = prev_latest + 1
            if mv.version in versions:
                raise MXNetError(
                    f"repository: model {name!r} version {mv.version} "
                    "already loaded (unload it first, or omit version= "
                    "for hot reload)")
            versions[mv.version] = mv
            self._latest[name] = max(prev_latest, mv.version)
            return mv.version, was_loaded, prev_latest

    def load(self, name, symbol=None, params=None, prefix=None, block=None,
             epoch=0, version=None):
        """Register a model version; returns the version number.  Loading
        an existing name again with no explicit version is a hot reload
        (latest+1) — which also kicks the warm hooks on a background
        thread, so the new version's bucket ladder compiles while the
        old version keeps serving."""
        symbol, params, input_names = _normalize(
            symbol=symbol, params=params, prefix=prefix, block=block,
            epoch=epoch)
        mv = _ModelVersion(symbol, params, input_names,
                           None if version is None else int(version))
        version, was_reload, prev_latest = self._register(name, mv)
        if was_reload:
            self._run_flip_hooks(name, mv, prev_latest)
        with self._lock:
            hooks_live = bool(self._warm_hooks)
        if was_reload and hooks_live:
            t = threading.Thread(target=self._run_warm_hooks,
                                 args=(name, mv), daemon=True,
                                 name=f"warmup-{name}-v{version}")
            t.start()
        return version

    def register_opaque(self, name, payload, version=None):
        """Version-allocate an **opaque** (non-Symbol) model payload
        through the same pointer-flip + flip-hook machinery as
        :meth:`load` — generation models (ISSUE 16) ride the
        repository's hot-reload semantics without a Symbol graph.  The
        payload lands in ``mv.params`` with ``mv.symbol is None`` (the
        opaque marker) and empty ``input_names``.

        Warm hooks are NOT run here: an opaque model's warmup is the
        caller's synchronous job (the generation engine AOT-warms the
        new version's decode/prefill ladders BEFORE calling this, so
        the flip observes the PR 7 warm-before-flip contract); flip
        hooks DO run on hot reload, which is what retires stale-version
        executors, decode ladders and prefix activations."""
        mv = _ModelVersion(None, payload, (),
                           None if version is None else int(version))
        version, was_reload, prev_latest = self._register(name, mv)
        if was_reload:
            self._run_flip_hooks(name, mv, prev_latest)
        return version

    def get(self, name, version=None):
        """The requested (or latest) ``_ModelVersion``."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise MXNetError(f"repository: unknown model {name!r}; "
                                 f"loaded: {sorted(self._models)}")
            if version is None:
                version = self._latest[name]
            mv = versions.get(int(version))
            if mv is None:
                raise MXNetError(
                    f"repository: model {name!r} has no version {version}; "
                    f"available: {sorted(versions)}")
            return mv

    def unload(self, name, version=None):
        """Drop one version (or the whole model when version is None)."""
        with self._lock:
            versions = self._models.get(name)
            if not versions:
                raise MXNetError(f"repository: unknown model {name!r}")
            if version is None:
                del self._models[name]
                del self._latest[name]
                return
            version = int(version)
            if version not in versions:
                raise MXNetError(
                    f"repository: model {name!r} has no version {version}")
            del versions[version]
            if not versions:
                del self._models[name]
                del self._latest[name]
            elif self._latest[name] == version:
                self._latest[name] = max(versions)

    def models(self):
        """{name: sorted list of loaded versions}."""
        with self._lock:
            return {n: sorted(v) for n, v in self._models.items()}

    def latest_version(self, name):
        with self._lock:
            if name not in self._latest:
                raise MXNetError(f"repository: unknown model {name!r}")
            return self._latest[name]

    # -- checkpoint-directory hot reload ------------------------------------
    def poll_checkpoint(self, name, ckpt_dir):
        """One poll of a checkpoint directory: when a step newer than the
        currently served version has COMMITTED, load it as a new version
        (version number == step) and return the step; else None.

        Only committed steps are ever considered — ``latest_step``
        cannot see a ``step-NNNNNN.tmp/`` in progress, and checksums are
        verified before the version goes live, so a torn or corrupt
        checkpoint is never served (ISSUE 2 satellite).

        A step that FAILS verification is quarantined (never re-read on
        later polls), the ``mxnet_serving_corrupt_ckpt_total`` alarm
        counter fires, and the poll degrades to the next-newest good
        committed step — the currently served version keeps serving
        either way, and the watch thread never wedges on a corrupt step
        (ISSUE 8 self-healing).

        The warm hooks run BEFORE the new version registers: a version
        swap under load compiles its whole bucket ladder first, so the
        flip never serves a cold-compile request (ISSUE 7 satellite).
        """
        from ..checkpoint import committed_steps, restore
        from ..checkpoint.core import CheckpointCorruptError
        _failpoint("serving/repository/poll")
        with self._lock:
            current = self._latest.get(name, 0)
            bad = set(self._corrupt_steps.get((name, ckpt_dir), ()))
        candidates = [s for s in committed_steps(ckpt_dir)
                      if s > current and s not in bad]
        for step in sorted(candidates, reverse=True):
            try:
                ckpt = restore(ckpt_dir, step=step)  # verifies checksums
            except CheckpointCorruptError as e:
                self._quarantine_step(name, ckpt_dir, step, e)
                continue  # degrade to the next-newest good step
            return self._load_checkpoint_version(name, ckpt)
        return None

    def _quarantine_step(self, name, ckpt_dir, step, exc):
        """Remember a corrupt step so no later poll re-reads it, and
        raise the alarm counter — this is an operator page, not a retry
        loop (docs/observability.md alarm catalog)."""
        import logging
        with self._lock:
            self._corrupt_steps.setdefault((name, ckpt_dir),
                                           set()).add(step)
        from .. import telemetry as _telemetry
        _telemetry.REGISTRY.counter(
            "mxnet_serving_corrupt_ckpt_total",
            "checkpoint steps that failed verification during serving "
            "hot-reload polls (quarantined; the old version kept "
            "serving)").inc(labels={"model": str(name)})
        _telemetry.flight.record("serving", "ckpt_quarantined",
                                 severity="error", model=str(name),
                                 step=step)
        logging.getLogger("mxnet_tpu.serving").error(
            "watch(%r): checkpoint step %d in %r failed verification "
            "(%s) — step quarantined, serving continues on the current "
            "version", name, step, ckpt_dir, exc)

    def corrupt_steps(self, name, ckpt_dir):
        """Steps quarantined by poll_checkpoint for (name, ckpt_dir)."""
        with self._lock:
            return sorted(self._corrupt_steps.get((name, ckpt_dir), ()))

    def _load_checkpoint_version(self, name, ckpt):
        from ..symbol import load_json
        if ckpt.symbol_json is None:
            raise MXNetError(
                f"repository.watch: checkpoint step {ckpt.step} holds "
                "no symbol — save it via CheckpointManager.save_module "
                "(or pass symbol=) so the server knows the graph")
        params = {}
        params.update(ckpt.arg_params)
        params.update(ckpt.aux_params)
        if not params:  # unprefixed tensor names: serve them as-is
            params = ckpt.as_ndarrays()
        symbol, params, input_names = _normalize(
            symbol=load_json(ckpt.symbol_json), params=params)
        mv = _ModelVersion(symbol, params, input_names, ckpt.step)
        # warm-before-flip, synchronously on this (watcher) thread: the
        # old version keeps serving while the ladder compiles
        self._run_warm_hooks(name, mv)
        _version, was_reload, prev_latest = self._register(name, mv)
        if was_reload:
            # post-flip drain+rebuild: stale-version executors retire,
            # the pool's admission state re-learns the new version
            self._run_flip_hooks(name, mv, prev_latest)
        return ckpt.step

    def watch(self, name, ckpt_dir, interval=None):
        """Hot-reload ``name`` from a CheckpointManager directory: a
        background poller picks up each newly committed step and loads
        it as a new version (in-flight batches finish on the version
        they resolved; the next batch serves the new step).  Returns the
        stop Event; ``unwatch(name)`` also stops it."""
        if interval is None:
            from ..config import get as _cfg
            interval = _cfg("MXNET_CKPT_WATCH_INTERVAL_S")
        self.unwatch(name)
        stop = threading.Event()

        def _poll_loop():
            import logging
            while not stop.is_set():
                try:
                    self.poll_checkpoint(name, ckpt_dir)
                except Exception:  # keep serving the current version
                    logging.getLogger("mxnet_tpu.serving").exception(
                        "watch(%r): poll of %r failed", name, ckpt_dir)
                stop.wait(interval)

        t = threading.Thread(target=_poll_loop, daemon=True,
                             name=f"ckpt-watch-{name}")
        with self._lock:
            self._watchers[name] = (t, stop)
        t.start()
        return stop

    def unwatch(self, name):
        """Stop the checkpoint watcher for ``name`` (no-op when absent)."""
        with self._lock:
            entry = self._watchers.pop(name, None)
        if entry is not None:
            t, stop = entry
            stop.set()
            if t.is_alive():
                t.join(timeout=5)

    def stop_watches(self):
        """Stop every active checkpoint watcher."""
        with self._lock:
            names = list(self._watchers)
        for n in names:
            self.unwatch(n)
