"""Serving observability: counters, gauges, latency percentiles.

Every ``ServingMetrics`` instance keeps its own thread-safe counters and
a bounded latency reservoir, mirrors every update into the profiler's
chrome-trace counter lanes (``profiler.record_counter``) so a running
``mx.profiler`` trace shows serving queue depth / throughput next to the
op timeline, and renders a ``snapshot()`` dict — the payload behind
``mx.serving.stats()``.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref

from .. import profiler as _profiler

# latency samples kept per metrics instance; percentile error from
# windowing is irrelevant at serving timescales and the bound keeps
# snapshot() O(window) regardless of uptime
_LATENCY_WINDOW = 4096

# all live metrics instances, for the module-level serving.stats()
_REGISTRY = weakref.WeakValueDictionary()
_REGISTRY_LOCK = threading.Lock()


def _percentile(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ServingMetrics:
    """Counters + latency reservoir for one server / batcher."""

    def __init__(self, name="serving"):
        self.name = name
        self._lock = threading.Lock()
        self._counters = collections.Counter()
        self._gauges = {}
        self._latencies_ms = collections.deque(maxlen=_LATENCY_WINDOW)
        self._reservoirs = {}   # name -> bounded deque (observe())
        self._batch_items = 0
        self._batch_slots = 0
        self._t_start = time.perf_counter()
        with _REGISTRY_LOCK:
            # last writer wins on a name collision (e.g. test reruns)
            _REGISTRY[name] = self

    # -- updates ------------------------------------------------------------
    def incr(self, key, n=1):
        with self._lock:
            self._counters[key] += n
            value = self._counters[key]
        _profiler.record_counter(f"serving:{self.name}:{key}", value)

    def gauge(self, key, value):
        with self._lock:
            self._gauges[key] = value
        _profiler.record_counter(f"serving:{self.name}:{key}", value)

    def get(self, key):
        with self._lock:
            return self._counters.get(key, self._gauges.get(key, 0))

    def observe_latency(self, ms):
        with self._lock:
            self._latencies_ms.append(float(ms))

    def observe(self, key, value):
        """Named bounded reservoir alongside the request-latency one —
        e.g. the generation engine's per-token ``intertoken_ms`` gaps;
        ``snapshot()`` renders p50/p90/p99 per key (ISSUE 16)."""
        with self._lock:
            res = self._reservoirs.get(key)
            if res is None:
                res = self._reservoirs[key] = collections.deque(
                    maxlen=_LATENCY_WINDOW)
            res.append(float(value))

    def drain_observations(self, key):
        """Return AND clear one named reservoir (windowed percentile
        measurement, like :meth:`drain_latencies`)."""
        with self._lock:
            res = self._reservoirs.get(key)
            out = list(res) if res else []
            if res:
                res.clear()
        return out

    def drain_latencies(self):
        """Return AND clear the latency reservoir — windowed percentile
        measurement (the bench spike phase compares the p99 of disjoint
        steady/spike windows on one live pool)."""
        with self._lock:
            out = list(self._latencies_ms)
            self._latencies_ms.clear()
        return out

    def observe_batch(self, n_real, n_slots):
        """One executed batch: ``n_real`` live requests in ``n_slots``
        padded slots (batch-occupancy accounting)."""
        with self._lock:
            self._counters["batches_total"] += 1
            self._batch_items += int(n_real)
            self._batch_slots += int(n_slots)
            occ = self._batch_items / max(1, self._batch_slots)
        _profiler.record_counter(
            f"serving:{self.name}:batch_occupancy", round(occ, 4))

    # -- snapshot -----------------------------------------------------------
    def snapshot(self):
        # copy the percentile reservoir UNDER the lock, sort OUTSIDE it:
        # a concurrent submit()/observe_latency() can never mutate the
        # sequence mid-sort, and the batcher's hot path never waits on an
        # O(n log n) sort held inside its metrics lock
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            lat = list(self._latencies_ms)
            reservoirs = {k: list(v)
                          for k, v in self._reservoirs.items() if v}
            items, slots = self._batch_items, self._batch_slots
            elapsed = max(1e-9, time.perf_counter() - self._t_start)
        lat.sort()
        responses = counters.get("responses_total", 0)
        snap = {
            "name": self.name,
            "uptime_s": round(elapsed, 3),
            "throughput_rps": round(responses / elapsed, 3),
            "latency_ms": {
                "p50": _percentile(lat, 50),
                "p90": _percentile(lat, 90),
                "p99": _percentile(lat, 99),
                "samples": len(lat),
            },
            "batch_occupancy": round(items / slots, 4) if slots else None,
        }
        for key, vals in sorted(reservoirs.items()):
            vals.sort()
            snap[key] = {"p50": _percentile(vals, 50),
                         "p90": _percentile(vals, 90),
                         "p99": _percentile(vals, 99),
                         "samples": len(vals)}
        snap.update(counters)
        snap.update(gauges)
        return snap


def stats():
    """Snapshot of every live metrics instance, keyed by name — the
    module-level ``mx.serving.stats()`` entry point.  This same payload
    feeds ``telemetry.snapshot()["serving"]`` and the Prometheus
    ``mxnet_serving_*`` families: once this module is imported, the
    telemetry registry's ``serving`` collector pulls from here, so the
    dict shape below IS the cross-subsystem contract."""
    with _REGISTRY_LOCK:
        instances = list(_REGISTRY.values())
    return {m.name: m.snapshot() for m in instances}
