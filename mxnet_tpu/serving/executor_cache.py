"""Compiled-executor cache with measured (or power-of-two) bucketing.

An inference ``Executor`` is expensive to create: binding traces the
graph and the first ``forward`` compiles one XLA program per input
signature.  The serving layer therefore never binds per request — it
buckets the batch dimension (by the model's planned ladder when
``mxnet_tpu.compile.BucketPlanner`` has measured one, else up to the
next power of two, so a Zipf of request sizes collapses onto few
programs), pads the inputs to the bucket, reuses one bound executor per
(model, version, bucketed signature) through an LRU, and slices the
padding back off the outputs.

Compilation lifecycle hooks (``mxnet_tpu.compile``, ISSUE 7): every
miss activates the persistent compilation cache and is counted by the
TraceLedger with its reason; a miss outside a warmed ladder logs an
unexpected-retrace WARN; per-model hit/miss/evict counters and
attributed compile seconds export through the telemetry registry as
``mxnet_executor_cache_*``.

The cache is shared machinery: ``ModelServer`` keys it by repository
(model, version), ``c_predict.Predictor`` keys it by content hash of the
symbol JSON + param bytes, so a host that creates a fresh Predictor per
request (the reference deployment shape) stops paying a rebind each
time.
"""
from __future__ import annotations

import collections
import threading
import weakref

import numpy as np

from ..base import MXNetError
from ..context import current_context
from .metrics import ServingMetrics

# process-wide cache metrics (hits/misses/evictions across every cache)
_CACHE_METRICS = ServingMetrics("executor_cache")

# every live cache, for the telemetry executor_cache pull-collector
_ALL_CACHES = weakref.WeakSet()
_ALL_CACHES_LOCK = threading.Lock()


def _executor_nbytes(executor):
    """Estimated device bytes a bound executor pins (args + aux), from
    shape metadata only — never a device sync."""
    from ..telemetry import resources as _resources
    total = 0
    for d in (getattr(executor, "arg_dict", None) or {},
              getattr(executor, "aux_dict", None) or {}):
        total += _resources.pytree_nbytes(dict(d))
    return total


def _ledger():
    from ..telemetry import resources as _resources
    return _resources.LEDGER


def bucket_batch(n, max_batch=None, ladder=None):
    """The bucket ``n`` runs at: the smallest planned-``ladder``
    boundary >= n when a measured ladder is given, else the next power
    of two, optionally capped at ``max_batch``.

    The cap wins even when it is not itself a power of two — the batcher
    never forms batches above ``max_batch``, so that one extra signature
    is the largest program ever compiled.
    """
    n = int(n)
    if n <= 0:
        raise MXNetError(f"bucket_batch: batch must be positive, got {n}")
    if max_batch is not None and n > int(max_batch):
        raise MXNetError(
            f"bucket_batch: batch {n} exceeds max_batch {max_batch}")
    if ladder:
        for b in ladder:  # planned ladders are ascending
            if b >= n:
                return int(b)
        # a stale plan that tops below n (max_batch raised since the
        # plan) falls through to the power-of-two policy
    b = 1
    while b < n:
        b <<= 1
    if max_batch is not None and b > int(max_batch):
        b = int(max_batch)
    return b


def shape_signature(input_shapes):
    """Canonical hashable signature for a dict of input shapes."""
    return tuple(sorted((str(k), tuple(int(d) for d in v))
                        for k, v in input_shapes.items()))


def feed_signature(feed):
    """Canonical hashable signature for a dict of host arrays — shapes
    AND dtypes, so an int32 feed never reuses a float32-bound program."""
    return tuple(sorted((str(k), tuple(int(d) for d in v.shape),
                         str(v.dtype))
                        for k, v in feed.items()))


def bind_inference_executor(symbol, params, input_shapes, ctx=None,
                            input_dtypes=None):
    """Bind ``symbol`` for inference: inputs get fresh zero buffers at
    ``input_shapes`` (dtype per ``input_dtypes``, default float32),
    every other argument / aux state comes from ``params`` (one flat
    name->NDArray dict).  grad_req='null' — the shared contract of
    c_predict.Predictor and the serving runner."""
    from .. import ndarray as nd
    ctx = ctx or current_context()
    aux_names = set(symbol.list_auxiliary_states())
    input_dtypes = input_dtypes or {}
    args = {}
    for name in symbol.list_arguments():
        if name in input_shapes:
            args[name] = nd.zeros(tuple(int(d) for d in input_shapes[name]),
                                  dtype=input_dtypes.get(name))
        elif name in params:
            args[name] = params[name]
        else:
            raise MXNetError(
                f"serving: argument {name!r} has neither a bound input "
                "shape nor a loaded parameter")
    aux = {name: params[name] for name in aux_names if name in params}
    return symbol.bind(ctx, args, grad_req="null", aux_states=aux)


class CachedExecutor:
    """A bound executor plus the lock serializing its users (the bound
    input buffers are shared mutable state).  ``_hot`` flips after the
    first forward (or ladder warmup): the compile that first forward
    triggers is attributed to the model in the TraceLedger."""

    __slots__ = ("executor", "lock", "key", "model", "_hot", "nbytes")

    def __init__(self, executor, key, model=None):
        self.executor = executor
        self.lock = threading.Lock()
        self.key = key
        self.model = model if model is not None else (
            key[0] if isinstance(key, tuple) and key else "?")
        self._hot = False
        # device footprint this entry pins (bound params + input/aux
        # buffers) — host shape arithmetic, charged to the ISSUE-13
        # device ledger at insert and released at evict
        self.nbytes = _executor_nbytes(executor)

    def run_padded(self, feed, n_real):
        """Write ``feed`` (already padded to the bound batch) into the
        input buffers, forward, and return outputs sliced to ``n_real``
        host arrays."""
        with self.lock:
            ex = self.executor
            for name, arr in feed.items():
                ex.arg_dict[name][:] = arr
            if self._hot:
                outs = ex.forward(is_train=False)
            else:
                # cold entry: this forward carries the trace + backend
                # compile — charge it to the model.  guarded_compile is
                # the corrupt-artifact fence: a persisted executable
                # that fails to load quarantines the cache namespace and
                # recompiles fresh instead of failing the request
                from .. import compile as _compile
                with _compile.LEDGER.attribute(str(self.model)):
                    outs = _compile.guarded_compile(
                        lambda: ex.forward(is_train=False),
                        what=f"first forward of {self.key!r}")
                self._hot = True
            # one device->host transfer per OUTPUT TENSOR (not per
            # request) — the batching already amortized the sync
            # graftlint: disable=host-sync-in-hot-path -- per-output boundary transfer, already batch-amortized
            return [np.asarray(o.asnumpy())[:n_real] for o in outs]


class ExecutorCache:
    """LRU of ``CachedExecutor`` keyed by (model-identity, signature)."""

    def __init__(self, capacity=None, name="cache"):
        if capacity is None:
            from .. import config as _config
            capacity = _config.get("MXNET_SERVING_EXECUTOR_CACHE")
        self.capacity = max(1, int(capacity))
        self.name = name
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._per_model = {}  # model -> {"hits"/"misses"/"evictions"}
        self._retire_hooks = []  # fn(model, keep_versions), flip-time
        with _ALL_CACHES_LOCK:
            _ALL_CACHES.add(self)

    def add_retire_hook(self, fn):
        """Register ``fn(model, keep_versions)`` to run whenever
        ``evict_stale_versions`` retires a flipped version — executors
        are not the only per-version state a hot swap must tear down:
        the generation engine hangs its decode/prefill ladders and
        prefix-cache activations here, so a stale version's compiled
        step or cached activations can never serve after the flip
        (ISSUE 16 small fix).  Hook failures are logged, never fatal —
        the executor eviction already happened."""
        with self._lock:
            self._retire_hooks.append(fn)
        return fn

    def _model_cell(self, model):
        cell = self._per_model.get(model)
        if cell is None:
            cell = self._per_model[model] = {
                "hits": 0, "misses": 0, "evictions": 0}
        return cell

    def get(self, key, builder, model=None, reason="request"):
        """Return the cached executor for ``key``, building (and possibly
        evicting LRU) on miss.  ``builder()`` -> bound Executor.

        The build runs under the cache lock on purpose: concurrent
        misses on one key must not compile the same program twice, and
        an inference bind is cheap relative to the XLA compile its first
        forward triggers anyway.  A miss activates the persistent
        compilation cache, records a (callsite, reason) trace in the
        TraceLedger, and — when it lands outside a warmed ladder — logs
        an unexpected-retrace WARN naming the signature.
        """
        if model is None and isinstance(key, tuple) and key:
            model = key[0]
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                self._model_cell(str(model))["hits"] += 1
                _CACHE_METRICS.incr("cache_hits_total")
                return entry
            self.misses += 1
            self._model_cell(str(model))["misses"] += 1
            _CACHE_METRICS.incr("cache_misses_total")
            from .. import compile as _compile
            _compile.ensure_persistent_cache()
            _compile.note_retrace(key, reason)
            # graftlint: disable=lock-order-cycle -- single-flight by design (docstring): concurrent misses on one key must not compile twice; builder never re-enters the cache
            entry = CachedExecutor(builder(), key, model=model)
            self._entries[key] = entry
            _ledger().add(str(model), "executor_cache", entry.nbytes)
            while len(self._entries) > self.capacity:
                _k, evicted = self._entries.popitem(last=False)
                self.evictions += 1
                self._model_cell(str(evicted.model))["evictions"] += 1
                _CACHE_METRICS.incr("cache_evictions_total")
                _ledger().release(str(evicted.model), "executor_cache",
                                  evicted.nbytes)
            return entry

    def evict_model(self, model_prefix):
        """Drop every entry whose key starts with ``model_prefix`` (used
        when a repository version is unloaded)."""
        with self._lock:
            doomed = [k for k in self._entries
                      if k[:len(model_prefix)] == model_prefix]
            for k in doomed:
                gone = self._entries.pop(k)
                _ledger().release(str(gone.model), "executor_cache",
                                  gone.nbytes)
            return len(doomed)

    def evict_stale_versions(self, model, keep_versions):
        """Hot-swap retirement: drop ``model``'s entries for every
        version NOT in ``keep_versions`` (typically {new, previous} —
        the previous stays warm for in-flight batches and a fast
        rollback).  In-flight users hold their own references, so
        eviction never tears an executing batch.  Registered retire
        hooks fire afterwards with the same ``(model, keep_versions)``
        so sibling per-version state (generation decode ladders,
        prefix-cache activations) retires in the same flip."""
        keep = set(keep_versions)
        with self._lock:
            doomed = [k for k in self._entries
                      if isinstance(k, tuple) and len(k) >= 2
                      and k[0] == model and k[1] not in keep]
            for k in doomed:
                gone = self._entries.pop(k)
                _ledger().release(str(gone.model), "executor_cache",
                                  gone.nbytes)
            hooks = list(self._retire_hooks)
        for fn in hooks:
            try:
                fn(model, keep)
            except Exception:  # the flip already happened; never unwind
                import logging
                logging.getLogger("mxnet_tpu.serving").exception(
                    "executor-cache retire hook %r failed for %s",
                    fn, model)
        return len(doomed)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self):
        with self._lock:
            return {"size": len(self._entries), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "per_model": {m: dict(c)
                                  for m, c in self._per_model.items()}}


def pad_to(arr, n_rows):
    """Zero-pad ``arr`` (host array, batch-leading) to ``n_rows``."""
    arr = np.asarray(arr)
    if arr.shape[0] == n_rows:
        return arr
    if arr.shape[0] > n_rows:
        raise MXNetError(
            f"pad_to: array batch {arr.shape[0]} exceeds target {n_rows}")
    pad = np.zeros((n_rows - arr.shape[0],) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


# the process-wide cache c_predict.Predictor binds through; sized by the
# same config knob as per-server caches
_SHARED = None
_SHARED_LOCK = threading.Lock()


def shared_cache():
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = ExecutorCache(name="shared")
        return _SHARED


# -- telemetry: per-model hit/miss/evict + attributed compile seconds --------
def stats_by_model():
    """Per-model counters aggregated across every live cache, plus the
    TraceLedger's attributed compile seconds (exact backend-compile time
    charged to each model by warmup / first-forward attribution)."""
    with _ALL_CACHES_LOCK:
        caches = list(_ALL_CACHES)
    merged = {}
    for cache in caches:
        for model, cell in cache.stats()["per_model"].items():
            out = merged.setdefault(
                model, {"hits": 0, "misses": 0, "evictions": 0,
                        "compile_s": 0.0, "compiles": 0})
            for k in ("hits", "misses", "evictions"):
                out[k] += cell[k]
    from .. import compile as _compile
    for model, attr in _compile.LEDGER.attributed().items():
        out = merged.setdefault(
            model, {"hits": 0, "misses": 0, "evictions": 0,
                    "compile_s": 0.0, "compiles": 0})
        out["compile_s"] += attr["compile_s"]
        out["compiles"] += attr["compiles"]
    return merged


def _executor_cache_samples():
    families = {
        "hits": ("mxnet_executor_cache_hits_total", "counter",
                 "serving executor-cache hits, by model"),
        "misses": ("mxnet_executor_cache_misses_total", "counter",
                   "serving executor-cache misses (bind + compile), "
                   "by model"),
        "evictions": ("mxnet_executor_cache_evictions_total", "counter",
                      "serving executor-cache LRU evictions, by model"),
        "compile_s": ("mxnet_executor_cache_compile_seconds_total",
                      "counter",
                      "backend compile seconds attributed to each "
                      "model's executors"),
    }
    out = []
    for model, cell in sorted(stats_by_model().items()):
        for field, (fam, mtype, help_) in families.items():
            out.append((fam, mtype, help_, {"model": model}, cell[field]))
    return out


def _register_collector():
    from .. import telemetry as _telemetry
    _telemetry.register_collector("executor_cache", stats_by_model,
                                  _executor_cache_samples)


_register_collector()
