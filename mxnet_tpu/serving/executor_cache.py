"""Compiled-executor cache with power-of-two shape bucketing.

An inference ``Executor`` is expensive to create: binding traces the
graph and the first ``forward`` compiles one XLA program per input
signature.  The serving layer therefore never binds per request — it
buckets the batch dimension up to the next power of two (so a Zipf of
request sizes collapses onto log2(max_batch) programs), pads the inputs
to the bucket, reuses one bound executor per (model, version, bucketed
signature) through an LRU, and slices the padding back off the outputs.

The cache is shared machinery: ``ModelServer`` keys it by repository
(model, version), ``c_predict.Predictor`` keys it by content hash of the
symbol JSON + param bytes, so a host that creates a fresh Predictor per
request (the reference deployment shape) stops paying a rebind each
time.
"""
from __future__ import annotations

import collections
import threading

import numpy as np

from ..base import MXNetError
from ..context import current_context
from .metrics import ServingMetrics

# process-wide cache metrics (hits/misses/evictions across every cache)
_CACHE_METRICS = ServingMetrics("executor_cache")


def bucket_batch(n, max_batch=None):
    """Next power of two >= n, optionally capped at ``max_batch``.

    The cap wins even when it is not itself a power of two — the batcher
    never forms batches above ``max_batch``, so that one extra signature
    is the largest program ever compiled.
    """
    n = int(n)
    if n <= 0:
        raise MXNetError(f"bucket_batch: batch must be positive, got {n}")
    b = 1
    while b < n:
        b <<= 1
    if max_batch is not None and b > int(max_batch):
        if n > int(max_batch):
            raise MXNetError(
                f"bucket_batch: batch {n} exceeds max_batch {max_batch}")
        b = int(max_batch)
    return b


def shape_signature(input_shapes):
    """Canonical hashable signature for a dict of input shapes."""
    return tuple(sorted((str(k), tuple(int(d) for d in v))
                        for k, v in input_shapes.items()))


def feed_signature(feed):
    """Canonical hashable signature for a dict of host arrays — shapes
    AND dtypes, so an int32 feed never reuses a float32-bound program."""
    return tuple(sorted((str(k), tuple(int(d) for d in v.shape),
                         str(v.dtype))
                        for k, v in feed.items()))


def bind_inference_executor(symbol, params, input_shapes, ctx=None,
                            input_dtypes=None):
    """Bind ``symbol`` for inference: inputs get fresh zero buffers at
    ``input_shapes`` (dtype per ``input_dtypes``, default float32),
    every other argument / aux state comes from ``params`` (one flat
    name->NDArray dict).  grad_req='null' — the shared contract of
    c_predict.Predictor and the serving runner."""
    from .. import ndarray as nd
    ctx = ctx or current_context()
    aux_names = set(symbol.list_auxiliary_states())
    input_dtypes = input_dtypes or {}
    args = {}
    for name in symbol.list_arguments():
        if name in input_shapes:
            args[name] = nd.zeros(tuple(int(d) for d in input_shapes[name]),
                                  dtype=input_dtypes.get(name))
        elif name in params:
            args[name] = params[name]
        else:
            raise MXNetError(
                f"serving: argument {name!r} has neither a bound input "
                "shape nor a loaded parameter")
    aux = {name: params[name] for name in aux_names if name in params}
    return symbol.bind(ctx, args, grad_req="null", aux_states=aux)


class CachedExecutor:
    """A bound executor plus the lock serializing its users (the bound
    input buffers are shared mutable state)."""

    __slots__ = ("executor", "lock", "key")

    def __init__(self, executor, key):
        self.executor = executor
        self.lock = threading.Lock()
        self.key = key

    def run_padded(self, feed, n_real):
        """Write ``feed`` (already padded to the bound batch) into the
        input buffers, forward, and return outputs sliced to ``n_real``
        host arrays."""
        with self.lock:
            ex = self.executor
            for name, arr in feed.items():
                ex.arg_dict[name][:] = arr
            outs = ex.forward(is_train=False)
            # one device->host transfer per OUTPUT TENSOR (not per
            # request) — the batching already amortized the sync
            # graftlint: disable=host-sync-in-hot-path -- per-output boundary transfer, already batch-amortized
            return [np.asarray(o.asnumpy())[:n_real] for o in outs]


class ExecutorCache:
    """LRU of ``CachedExecutor`` keyed by (model-identity, signature)."""

    def __init__(self, capacity=None):
        if capacity is None:
            from .. import config as _config
            capacity = _config.get("MXNET_SERVING_EXECUTOR_CACHE")
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key, builder):
        """Return the cached executor for ``key``, building (and possibly
        evicting LRU) on miss.  ``builder()`` -> bound Executor.

        The build runs under the cache lock on purpose: concurrent
        misses on one key must not compile the same program twice, and
        an inference bind is cheap relative to the XLA compile its first
        forward triggers anyway.
        """
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                _CACHE_METRICS.incr("cache_hits_total")
                return entry
            self.misses += 1
            _CACHE_METRICS.incr("cache_misses_total")
            entry = CachedExecutor(builder(), key)
            self._entries[key] = entry
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
                _CACHE_METRICS.incr("cache_evictions_total")
            return entry

    def evict_model(self, model_prefix):
        """Drop every entry whose key starts with ``model_prefix`` (used
        when a repository version is unloaded)."""
        with self._lock:
            doomed = [k for k in self._entries
                      if k[:len(model_prefix)] == model_prefix]
            for k in doomed:
                del self._entries[k]
            return len(doomed)

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def stats(self):
        with self._lock:
            return {"size": len(self._entries), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


def pad_to(arr, n_rows):
    """Zero-pad ``arr`` (host array, batch-leading) to ``n_rows``."""
    arr = np.asarray(arr)
    if arr.shape[0] == n_rows:
        return arr
    if arr.shape[0] > n_rows:
        raise MXNetError(
            f"pad_to: array batch {arr.shape[0]} exceeds target {n_rows}")
    pad = np.zeros((n_rows - arr.shape[0],) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


# the process-wide cache c_predict.Predictor binds through; sized by the
# same config knob as per-server caches
_SHARED = None
_SHARED_LOCK = threading.Lock()


def shared_cache():
    global _SHARED
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = ExecutorCache()
        return _SHARED
