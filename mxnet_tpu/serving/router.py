"""ReplicaPool: load-aware routing + SLO admission over batcher replicas.

PR 1's serving stack was ONE ``DynamicBatcher`` per (model, signature)
on one host — throughput capped by a single worker loop, tail latency
queue-depth-bound.  The pool puts a router in front of K batcher
replicas (one per device, or K per device for intra-device concurrency:
Opara's stream-concurrency argument, PAPERS.md, maps to running
independent micro-batches concurrently while the shared executor lock
serializes only the device program itself):

* **load-aware routing** — each submit goes to the replica with the
  smallest *predicted drain time*: ``occupancy()`` (queued + staged +
  executing requests) x the pool's per-request service-time EWMA.  Ties
  break by replica id, so an idle pool round-robins trivially.
* **graceful spill** — a replica that sheds (``ServingOverloadError``),
  is draining (``ServingClosedError``), has failed fast
  (``ServingWorkerError(exhausted=True)``) or takes an injected
  dispatch fault spills the request to the next-least-loaded sibling;
  ``mxnet_serving_router_spill_total{model}`` counts every rescued hop.
  Only when EVERY replica refuses does the pool re-raise.  A malformed
  request (validator rejection) still fails alone — never spilled.
* **SLO admission control** — ``slo_p99_ms`` (or
  ``MXNET_SERVING_SLO_P99_MS``) sheds on *predicted* p99: the
  service-rate EWMA (sampled from the same metrics the telemetry
  registry exports) x pool occupancy, per model — so the shed
  watermark self-tunes to the model's measured service rate instead of
  a hand-picked queue depth.  Excess traffic fails as typed
  ``ServingOverloadError`` carrying ``predicted_p99_ms``/``slo_ms``.
* **drain-on-removal** — ``remove_replica`` stops intake on that
  replica and drains everything it admitted before returning; requests
  are never dropped by a scale-down or a kill (chaos scenario
  ``replica_kill_mid_burst``).

Telemetry: ``mxnet_serving_replica_occupancy{model,replica}``,
``mxnet_serving_router_spill_total{model}`` and
``mxnet_serving_predicted_p99_ms{model}`` export through the process
registry (docs/observability.md).
"""
from __future__ import annotations

import threading
import time

from ..base import MXNetError
from ..chaos.failpoints import ChaosInjectedError
from ..chaos.failpoints import failpoint as _failpoint
from ..telemetry import flight as _flight
from ..telemetry import trace as _trace
from .batcher import (DynamicBatcher, ServingClosedError,
                      ServingOverloadError, ServingWorkerError)
from .metrics import ServingMetrics


def _registry():
    from .. import telemetry as _telemetry
    return _telemetry.REGISTRY


def _occupancy_gauge():
    return _registry().gauge(
        "mxnet_serving_replica_occupancy",
        "requests owned by each serving replica (queued + staged + "
        "executing), sampled at every routing decision")


def _spill_counter():
    return _registry().counter(
        "mxnet_serving_router_spill_total",
        "requests the router re-routed to a sibling replica after the "
        "chosen replica shed, drained, failed fast, or took an injected "
        "dispatch fault")


def _predicted_p99_gauge():
    return _registry().gauge(
        "mxnet_serving_predicted_p99_ms",
        "the admission controller's predicted p99 (pool occupancy / "
        "service-rate EWMA) at the last admission decision, per model; "
        "requests are shed as ServingOverloadError once this crosses "
        "the MXNET_SERVING_SLO_P99_MS SLO")


class AdmissionController:
    """Predicted-p99 SLO admission for one pool (one model).

    The predictor is deliberately simple and self-tuning: a new request
    admitted behind ``occupancy`` in-flight requests waits roughly
    ``occupancy / service_rate`` — the time the pool needs to drain
    everything ahead of it.  ``service_rate`` (responses/s) is an EWMA
    sampled from the pool's response counter, so a slower model (or a
    degraded pool) AUTOMATICALLY lowers the depth at which shedding
    starts; no hand-tuned watermark tracks the model's speed.
    Prediction leads measurement: the request that WOULD have blown the
    p99 is shed before it queues, which is what keeps the spike p99
    bounded (bench gate ``serve_spike_p99_ms``).
    """

    # ignore samples shorter than this (rate estimates from sub-20ms
    # windows are dominated by scheduler jitter)
    MIN_SAMPLE_S = 0.02

    def __init__(self, name, slo_p99_ms=None, alpha=None):
        from .. import config as _config
        self.name = name
        self.slo_p99_ms = float(
            slo_p99_ms if slo_p99_ms is not None
            else _config.get("MXNET_SERVING_SLO_P99_MS"))
        self.alpha = float(alpha if alpha is not None
                           else _config.get("MXNET_SERVING_SLO_EWMA_ALPHA"))
        self._lock = threading.Lock()
        self._rate_ewma = None   # responses / s
        self._last = None        # (responses_total, perf_counter)

    def reset(self):
        """Forget the learned service rate (hot-swap rebuild: a new
        model version re-learns its own rate before shedding on it)."""
        with self._lock:
            self._rate_ewma = None
            self._last = None

    def observe(self, responses_total, occupancy, now=None):
        """Feed one (response counter, occupancy) sample; updates the
        service-rate EWMA.  Idle windows (no completions, nothing
        pending) only advance the sample anchor — they must not decay
        the learned rate, or every burst would start with a shed storm."""
        now = time.perf_counter() if now is None else now
        with self._lock:
            if self._last is None:
                self._last = (responses_total, now)
                return
            r0, t0 = self._last
            dt = now - t0
            if dt < self.MIN_SAMPLE_S:
                return
            dresp = responses_total - r0
            if dresp > 0:
                inst = dresp / dt
            elif occupancy > 0:
                # work pending but nothing completed across the window:
                # the true rate is below 1/dt — decay toward it
                inst = 1.0 / dt
            else:
                self._last = (responses_total, now)
                return
            if self._rate_ewma is None:
                self._rate_ewma = inst
            else:
                self._rate_ewma = (self.alpha * inst
                                   + (1.0 - self.alpha) * self._rate_ewma)
            self._last = (responses_total, now)

    def service_rate(self):
        with self._lock:
            return self._rate_ewma

    def predicted_p99_ms(self, occupancy):
        """Predicted wait (ms) for a request admitted NOW behind
        ``occupancy`` pending requests; None while the rate is unknown
        (cold pools admit — there is nothing to predict from)."""
        with self._lock:
            rate = self._rate_ewma
        if rate is None or rate <= 0:
            return None
        return (occupancy + 1) / rate * 1e3

    def check(self, occupancy):
        """Admission decision; returns the predicted p99 (ms) it was
        made on (None = no prediction yet).  Raises
        ``ServingOverloadError`` when the prediction breaches the SLO."""
        predicted = self.predicted_p99_ms(occupancy)
        if predicted is not None:
            _predicted_p99_gauge().set(predicted,
                                       labels={"model": self.name})
        if self.slo_p99_ms > 0 and predicted is not None \
                and predicted > self.slo_p99_ms:
            raise ServingOverloadError(
                self.name, occupancy, None,
                predicted_p99_ms=predicted, slo_ms=self.slo_p99_ms)
        return predicted


class ReplicaPool:
    """K ``DynamicBatcher`` replicas behind one load-aware router.

    ``runner_factory(replica_id)`` builds each replica's runner (the
    same callable may be shared — the executor cache already serializes
    the device program; replicas then overlap all the HOST work:
    coalescing, stacking, padding, validation, result fan-out).
    Replicas share the pool's ``ServingMetrics``, so ``stats()`` stays
    one aggregate per model endpoint.
    """

    def __init__(self, runner_factory, num_replicas=None, name="pool",
                 model=None, metrics=None, validator=None,
                 slo_p99_ms=None, **batcher_kw):
        from .. import config as _config
        n = int(num_replicas if num_replicas is not None
                else _config.get("MXNET_SERVING_REPLICAS"))
        if n <= 0:
            raise MXNetError("serving: num_replicas must be positive")
        self.name = name
        self.model = str(model if model is not None else name)
        self._runner_factory = runner_factory
        self.metrics = metrics or ServingMetrics(name)
        self._validator = validator
        self._batcher_kw = dict(batcher_kw)
        self.admission = AdmissionController(self.model,
                                             slo_p99_ms=slo_p99_ms)
        self._lock = threading.Lock()
        self._replicas = {}   # rid -> DynamicBatcher
        self._next_rid = 0
        self._closed = False
        # pool-local completion counter: the admission EWMA must see
        # THIS model's service rate even when the ServingMetrics object
        # is shared server-wide across models
        self._responses = 0
        self._route_n = 0
        for _ in range(n):
            self.add_replica()

    # -- replica lifecycle ---------------------------------------------------
    def _counted(self, runner):
        def run(feed, n_real):
            out = runner(feed, n_real)
            with self._lock:
                self._responses += n_real
            return out
        return run

    def responses(self):
        """Requests this pool completed (the admission EWMA's input)."""
        with self._lock:
            return self._responses

    def _make_replica(self, rid):
        return DynamicBatcher(
            self._counted(self._runner_factory(rid)),
            name=f"{self.name}/r{rid}", metrics=self.metrics,
            validator=self._validator, **self._batcher_kw)

    def add_replica(self):
        """Scale up by one replica; returns its id."""
        with self._lock:
            if self._closed:
                raise ServingClosedError(self.name)
            rid = self._next_rid
            self._next_rid += 1
            self._replicas[rid] = self._make_replica(rid)
        _flight.record("serving", "replica_added", model=self.model,
                       replica=rid)
        return rid

    def remove_replica(self, rid, drain=True, timeout=30.0):
        """Scale down: stop intake on replica ``rid``, drain everything
        it admitted (default), and drop it from routing.  Returns the
        closed batcher.  Requests in its queue run to completion —
        removal never drops admitted work."""
        with self._lock:
            b = self._replicas.pop(int(rid), None)
            live = sorted(self._replicas)
        if b is None:
            raise MXNetError(
                f"serving[{self.name}]: no replica {rid}; live: {live}")
        b.close(drain=drain, timeout=timeout)
        _occupancy_gauge().set(0, labels={"model": self.model,
                                          "replica": str(rid)})
        _flight.record("serving", "replica_removed", model=self.model,
                       replica=rid, drained=bool(drain))
        return b

    def resize(self, num_replicas, drain=True):
        """Grow or shrink to ``num_replicas`` (highest-id replicas are
        drained first on shrink)."""
        n = int(num_replicas)
        if n <= 0:
            raise MXNetError("serving: num_replicas must be positive")
        while len(self.replica_ids()) < n:
            self.add_replica()
        while len(self.replica_ids()) > n:
            self.remove_replica(max(self.replica_ids()), drain=drain)

    def replica_ids(self):
        with self._lock:
            return sorted(self._replicas)

    def replica(self, rid):
        with self._lock:
            return self._replicas[int(rid)]

    # how often the routing path exports the per-replica occupancy
    # gauges: every submit would double the lock traffic of a
    # fully-shedding overload loop for a metric nobody reads at that
    # granularity (a scrape sees one sample either way)
    _GAUGE_EVERY = 32

    # -- routing -------------------------------------------------------------
    def _ranked_replicas(self):
        """Live replicas ranked by predicted drain time (occupancy x
        the shared service-time EWMA — with one EWMA per pool the rank
        reduces to occupancy, ties broken by id), periodically exporting
        the occupancy gauges as a side effect."""
        with self._lock:
            replicas = sorted(self._replicas.items())
            self._route_n += 1
            export = self._route_n % self._GAUGE_EVERY == 1
        ranked = []
        gauge = _occupancy_gauge() if export else None
        for rid, b in replicas:
            occ = b.occupancy()
            if gauge is not None:
                gauge.set(occ, labels={"model": self.model,
                                       "replica": str(rid)})
            ranked.append((occ, rid, b))
        ranked.sort(key=lambda t: (t[0], t[1]))
        return ranked

    def submit(self, inputs, timeout_ms=None, trace=None):
        """Route one request: SLO admission, then least-predicted-drain
        replica, spilling to siblings on shed/drain/failure.  Raises
        ``ServingOverloadError`` (typed, synchronous) when admission
        predicts an SLO breach or every replica sheds.

        ``trace`` (an end-to-end trace context) survives spill hops:
        the SAME context rides the resubmission to each sibling, so a
        request that sheds, spills and resolves elsewhere is still ONE
        trace with its hops recorded as events."""
        tr = trace if trace is not None else _trace.NULL_TRACE
        ranked = self._ranked_replicas()
        if not ranked:
            self.metrics.incr("rejected_total")
            raise ServingClosedError(self.name)
        total_occ = sum(occ for occ, _rid, _b in ranked)
        self.admission.observe(self.responses(), total_occ)
        try:
            predicted = self.admission.check(total_occ)
        except ServingOverloadError as e:
            self.metrics.incr("shed_total")
            self.metrics.incr("slo_shed_total")
            tr.event("admission", verdict="shed",
                     predicted_p99_ms=e.predicted_p99_ms,
                     slo_ms=e.slo_ms)
            tr.finish(status="shed")
            _flight.record("serving", "slo_shed", severity="warn",
                           model=self.model, occupancy=total_occ,
                           predicted_p99_ms=e.predicted_p99_ms)
            raise
        tr.event("admission", verdict="admit", occupancy=total_occ,
                 predicted_p99_ms=predicted)
        last_exc = None
        for hop, (_occ, rid, b) in enumerate(ranked):
            if b.failed:
                last_exc = ServingWorkerError(b.name, exhausted=True)
                continue
            try:
                _failpoint("serving/router/dispatch")
                tr.event("route", replica=rid, hop=hop)
                fut = b.submit(inputs, timeout_ms=timeout_ms, trace=tr)
            except (ServingOverloadError, ServingClosedError,
                    ServingWorkerError, ChaosInjectedError) as e:
                # shed / draining / failed-fast / injected dispatch
                # fault: spill to the next-least-loaded sibling.  Any
                # other error (validator rejection, malformed inputs)
                # is about THIS request and propagates — a bad request
                # fails alone, it is never spilled K times
                tr.event("spill", replica=rid, hop=hop,
                         cause=type(e).__name__)
                last_exc = e
                continue
            if hop > 0:
                self.metrics.incr("spill_total", hop)
                _spill_counter().inc(hop, labels={"model": self.model})
                _flight.record("serving", "spill", severity="warn",
                               model=self.model, hops=hop, replica=rid)
            return fut
        tr.event("refused", hops=len(ranked),
                 cause=type(last_exc).__name__)
        tr.finish(status="refused")
        raise last_exc  # every replica refused (all typed errors)

    # -- observability / lifecycle -------------------------------------------
    def stats(self):
        with self._lock:
            replicas = sorted(self._replicas.items())
        occ = {rid: b.occupancy() for rid, b in replicas}
        return {
            "replicas": len(replicas),
            "replica_ids": [rid for rid, _ in replicas],
            "occupancy": occ,
            "failed_replicas": [rid for rid, b in replicas if b.failed],
            "service_rate_rps": self.admission.service_rate(),
            "predicted_p99_ms":
                self.admission.predicted_p99_ms(sum(occ.values())),
            "slo_p99_ms": self.admission.slo_p99_ms,
        }

    def close(self, drain=True, timeout=30.0):
        """Stop intake pool-wide and drain (default) every replica."""
        with self._lock:
            self._closed = True
            replicas = list(self._replicas.items())
            self._replicas.clear()
        for rid, b in replicas:
            b.close(drain=drain, timeout=timeout)
            _occupancy_gauge().set(0, labels={"model": self.model,
                                              "replica": str(rid)})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
